"""Mesh-sharded DDD engine (parallel/ddd_shard_engine.py).

The scale architecture's multi-chip composition: host-exact dedup
partitioned over the mesh's fingerprint-owner map, canonical
(level, window, shard) discovery order.  Gates: oracle-exact totals on
the 8-device virtual CPU mesh, ndev-invariance, IDENTITY with the
single-chip DDD engine on a 1-device mesh (order and checkpoint
included), parity under forced filter eviction, valid replayable
violation/deadlock counterexamples, window-boundary checkpoint/resume,
and checkpoint resharding across mesh sizes (including adopting a
single-chip campaign checkpoint onto a mesh).
"""

import dataclasses

import numpy as np
import pytest

# needs the virtual multi-device mesh — the slowest compiles on
# this 1-core host, excluded from the time-boxed tier-1 window
# (-m 'not slow'); the shard family stays exercised via -m smoke.
pytestmark = pytest.mark.slow

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.models import interp, refbfs, spec as S
from raft_tla_tpu.ops import msgbits as mb
from raft_tla_tpu.parallel.ddd_shard_engine import (
    DDDShardCapacities, DDDShardEngine, reshard_ddd_checkpoint)
from raft_tla_tpu.parallel.shard_engine import make_mesh, make_slice_mesh

CFG = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                max_log=0, max_msgs=2),
                  spec="election", invariants=("NoTwoLeaders",), chunk=32)
CAPS = DDDShardCapacities(block=256, table=1 << 14, seg_rows=1 << 14,
                          flush=1 << 10, levels=64)


def assert_totals(got, ref):
    assert got.n_states == ref.n_states
    assert got.diameter == ref.diameter
    assert got.levels == ref.levels
    assert got.n_transitions == ref.n_transitions
    assert sum(got.coverage.values()) == sum(ref.coverage.values())


@pytest.mark.parametrize("host_dedup", ["on", "off"])
def test_election_2server_parity_8dev(host_dedup, monkeypatch):
    monkeypatch.setenv("RAFT_TLA_HOSTDEDUP", host_dedup)
    ref = refbfs.check(CFG)
    got = DDDShardEngine(CFG, make_mesh(8), CAPS).check()
    assert_totals(got, ref)
    assert got.n_states == 3014 and got.diameter == 17
    assert got.violation is None


def test_host_dedup_checkpoint_cross_gate_4dev(tmp_path, monkeypatch):
    """Per-shard partitioned masters rebuild from the same gate-agnostic
    key log: a snapshot written under either arm resumes under the
    other, byte-identical, with the canonical (level, window, shard)
    order untouched."""
    mesh = make_mesh(4)
    straight = DDDShardEngine(CFG, mesh, CAPS).check()
    for write, read in (("on", "off"), ("off", "on")):
        ck = str(tmp_path / f"shard_{write}.ckpt")
        monkeypatch.setenv("RAFT_TLA_HOSTDEDUP", write)
        DDDShardEngine(CFG, mesh, CAPS).check(checkpoint=ck,
                                              checkpoint_every_s=0.0)
        monkeypatch.setenv("RAFT_TLA_HOSTDEDUP", read)
        resumed = DDDShardEngine(CFG, mesh, CAPS).check(resume=ck)
        assert_totals(resumed, straight)
        assert resumed.coverage == straight.coverage
        assert resumed.violation is None


def test_single_dev_mesh_equals_single_chip():
    """ndev=1: canonical order degenerates to the single-chip DDD
    engine's stream order — coverage attribution (order-dependent)
    must match refbfs exactly, not just in total."""
    ref = refbfs.check(CFG)
    got = DDDShardEngine(CFG, make_mesh(1), CAPS).check()
    assert_totals(got, ref)
    assert got.coverage == ref.coverage


def test_ndev_invariance():
    runs = {n: DDDShardEngine(CFG, make_mesh(n), CAPS).check()
            for n in (1, 2, 8)}
    base = runs[1]
    for n, r in runs.items():
        assert r.n_states == base.n_states, n
        assert r.levels == base.levels, n
        assert r.n_transitions == base.n_transitions, n


def test_multi_segment_windows_8dev():
    """Windows needing several device dispatches (tiny segment budget +
    near-full output buffers) must work: the first continuation call
    passes a committed-sharding chunk cursor, which retraces the pjit —
    a build-time-closure leak crashed exactly here (review regression).
    seg_rows is just past the one-chunk receivable bound, so buffer-full
    halts fire too."""
    import math

    ref = refbfs.check(CFG)
    nr = 8 * CFG.chunk * 11          # ndev * chunk * A upper bound
    caps = DDDShardCapacities(block=256, table=1 << 14,
                              seg_rows=1 << max(12, math.ceil(
                                  math.log2(nr + 1))),
                              flush=1 << 10, levels=64)
    eng = DDDShardEngine(CFG, make_mesh(8), caps, seg_chunks=4)
    got = eng.check()
    assert_totals(got, ref)


def test_parity_under_forced_eviction_8dev():
    """A 128-slot per-shard filter evicts constantly on a 3014-state
    space; the sharded host dedup must absorb every re-sight."""
    ref = refbfs.check(CFG)
    caps = DDDShardCapacities(block=256, table=1 << 7, seg_rows=1 << 14,
                              flush=1 << 9, levels=64)
    got = DDDShardEngine(CFG, make_mesh(8), caps).check()
    assert_totals(got, ref)


def test_slice_mesh_2x4_parity():
    ref = refbfs.check(CFG)
    got = DDDShardEngine(CFG, make_slice_mesh(2, 4), CAPS).check()
    assert_totals(got, ref)


def test_symmetry_composes_8dev():
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=("NoTwoLeaders",),
                      symmetry=("Server",), chunk=32)
    ref = refbfs.check(cfg)
    got = DDDShardEngine(cfg, make_mesh(8), CAPS).check()
    assert_totals(got, ref)
    assert got.n_states == 1514


def test_violation_trace_replayable_8dev():
    """Seeded NaiveNoTwoLeaders violation: the counterexample may be a
    different one than refbfs's (chunk-granular relaxed stop, as
    shard_engine), but must start at Init, follow real transitions, and
    violate the same invariant."""
    from raft_tla_tpu.models import invariants as inv_mod

    bounds = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                    max_msgs=4, max_dup=1)
    cfg = CheckConfig(bounds=bounds, spec="election",
                      invariants=("NaiveNoTwoLeaders",), chunk=64)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3),
        votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100),
        msgs=tuple(sorted((m, 1) for m in
                          (mb.rv_response(3, 1, 1, 2),))),
    )
    caps = DDDShardCapacities(block=1 << 12, table=1 << 14,
                              seg_rows=1 << 15, flush=1 << 12, levels=64)
    got = DDDShardEngine(cfg, make_mesh(8), caps).check(
        init_override=start)
    assert got.violation is not None
    assert got.violation.invariant == "NaiveNoTwoLeaders"
    trace = got.violation.trace
    assert trace[0][0] is None and trace[0][1] == start
    for (_l, prev), (_label, cur) in zip(trace, trace[1:]):
        succs = [t for _i, t in interp.successors(prev, bounds,
                                                  spec="election")]
        assert cur in succs
    assert not inv_mod.py_invariant("NaiveNoTwoLeaders")(
        got.violation.state, bounds)


def test_deadlock_detected_8dev():
    cfg = CheckConfig(bounds=Bounds(n_servers=1, n_values=1, max_term=2,
                                    max_log=0, max_msgs=2),
                      spec="election", invariants=(), chunk=16,
                      check_deadlock=True)
    ref = refbfs.check(cfg)
    caps = DDDShardCapacities(block=64, table=1 << 7, seg_rows=1 << 12,
                              flush=1 << 8, levels=64)
    got = DDDShardEngine(cfg, make_mesh(8), caps).check()
    assert ref.violation is not None and got.violation is not None
    assert got.violation.invariant == ref.violation.invariant  # DEADLOCK
    # the dead state must genuinely have no successors
    dead = got.violation.state
    assert not list(interp.successors(dead, cfg.bounds, spec="election"))


def test_routing_overflow_is_loud():
    caps = DDDShardCapacities(block=256, table=1 << 14, seg_rows=1 << 14,
                              flush=1 << 10, levels=64, send=1)
    with pytest.raises(RuntimeError, match="routing budget"):
        DDDShardEngine(CFG, make_mesh(8), caps).check()


def test_checkpoint_resume_exact_8dev(tmp_path):
    ck = str(tmp_path / "dddsh.ckpt")
    mesh = make_mesh(8)
    straight = DDDShardEngine(CFG, mesh, CAPS).check()
    res = DDDShardEngine(CFG, mesh, CAPS).check(checkpoint=ck,
                                                checkpoint_every_s=0.0)
    assert res.n_states == straight.n_states
    resumed = DDDShardEngine(CFG, mesh, CAPS).check(resume=ck)
    assert resumed.n_states == straight.n_states
    assert resumed.levels == straight.levels
    assert resumed.n_transitions == straight.n_transitions
    assert resumed.coverage == res.coverage   # identical canonical order
    assert resumed.violation is None

    # a different mesh size must refuse the snapshot (owner map changed)
    with pytest.raises(ValueError, match="digest|different model"):
        DDDShardEngine(CFG, make_mesh(4), CAPS).check(resume=ck)


def test_reshard_across_mesh_sizes(tmp_path):
    """8 -> 2 devices with equal global window size (block scaled 4x):
    every window boundary is shared, the streams move verbatim, and the
    resumed run completes with oracle-exact totals."""
    ck8 = str(tmp_path / "m8.ckpt")
    ck2 = str(tmp_path / "m2.ckpt")
    DDDShardEngine(CFG, make_mesh(8), CAPS).check(
        checkpoint=ck8, checkpoint_every_s=0.0)
    caps2 = DDDShardCapacities(block=1024, table=1 << 14,
                               seg_rows=1 << 14, flush=1 << 10, levels=64)
    info = reshard_ddd_checkpoint(CFG, CAPS, ck8, ck2, ndev_src=8,
                                  ndev_dst=2, caps_dst=caps2)
    assert info["ndev_dst"] == 2
    ref = refbfs.check(CFG)
    got = DDDShardEngine(CFG, make_mesh(2), caps2).check(resume=ck2)
    assert_totals(got, ref)


def test_adopt_single_chip_checkpoint(tmp_path):
    """A single-chip DDD campaign checkpoint migrates onto the mesh:
    ndev_src=1 with the single-chip block inside caps_src (the stream
    formats are identical by design)."""
    from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine

    ck1 = str(tmp_path / "chip.ckpt")
    ckm = str(tmp_path / "mesh.ckpt")
    sc_caps = DDDCapacities(block=1024, table=1 << 14, flush=1 << 10,
                            levels=64)
    DDDEngine(CFG, sc_caps).check(checkpoint=ck1, checkpoint_every_s=0.0)
    caps_src = DDDShardCapacities(block=1024, table=1 << 14,
                                  seg_rows=1 << 14, flush=1 << 10,
                                  levels=64)
    caps_dst = DDDShardCapacities(block=256, table=1 << 14,
                                  seg_rows=1 << 14, flush=1 << 10,
                                  levels=64)
    reshard_ddd_checkpoint(CFG, caps_src, ck1, ckm, ndev_src=1,
                           ndev_dst=4, caps_dst=caps_dst)
    ref = refbfs.check(CFG)
    got = DDDShardEngine(CFG, make_mesh(4), caps_dst).check(resume=ckm)
    assert_totals(got, ref)


def test_cp_mode_parity_8dev():
    """CP mode (lane-sliced expansion over a replicated window) must
    explore the identical state graph: oracle-exact totals on an
    m4-heavy config where the bag lanes dominate the fan-out — the
    regime SURVEY §2.9's CP row targets."""
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=4, max_dup=2),
                      spec="election", invariants=("NoTwoLeaders",),
                      chunk=32)
    ref = refbfs.check(cfg)
    caps = DDDShardCapacities(block=256, table=1 << 12, seg_rows=1 << 15,
                              flush=1 << 10, levels=64, cp=True)
    got = DDDShardEngine(cfg, make_mesh(8), caps).check()
    assert_totals(got, ref)
    # every lane family still gets credited (lane ids are table-dense)
    assert got.coverage.keys() == ref.coverage.keys()


def test_cp_mode_deadlock_and_violation():
    """The cross-shard enabled-lane psum must not miss deadlocks, and
    violations carry valid traces (dense lane labels)."""
    from raft_tla_tpu.models import invariants as inv_mod

    dl = CheckConfig(bounds=Bounds(n_servers=1, n_values=1, max_term=2,
                                   max_log=0, max_msgs=2),
                     spec="election", invariants=(), chunk=16,
                     check_deadlock=True)
    caps = DDDShardCapacities(block=64, table=1 << 7, seg_rows=1 << 12,
                              flush=1 << 8, levels=64, cp=True)
    ref = refbfs.check(dl)
    got = DDDShardEngine(dl, make_mesh(8), caps).check()
    assert got.violation is not None
    assert got.violation.invariant == ref.violation.invariant
    assert not list(interp.successors(got.violation.state, dl.bounds,
                                      spec="election"))

    bounds = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0,
                    max_msgs=4, max_dup=1)
    vcfg = CheckConfig(bounds=bounds, spec="election",
                       invariants=("NaiveNoTwoLeaders",), chunk=64)
    start = interp.init_state(bounds)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3), votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100),
        msgs=tuple(sorted((m, 1) for m in
                          (mb.rv_response(3, 1, 1, 2),))))
    caps_v = DDDShardCapacities(block=1 << 12, table=1 << 14,
                                seg_rows=1 << 16, flush=1 << 12,
                                levels=64, cp=True)
    gv = DDDShardEngine(vcfg, make_mesh(8), caps_v).check(
        init_override=start)
    assert gv.violation is not None
    assert gv.violation.invariant == "NaiveNoTwoLeaders"
    trace = gv.violation.trace
    for (_l, prev), (_label, cur) in zip(trace, trace[1:]):
        succs = [t for _i, t in interp.successors(prev, bounds,
                                                  spec="election")]
        assert cur in succs
    assert not inv_mod.py_invariant("NaiveNoTwoLeaders")(
        gv.violation.state, bounds)


def test_cp_mode_checkpoint_resume(tmp_path):
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=0, max_msgs=4, max_dup=2),
                      spec="election", invariants=("NoTwoLeaders",),
                      chunk=32)
    caps = DDDShardCapacities(block=256, table=1 << 12, seg_rows=1 << 15,
                              flush=1 << 10, levels=64, cp=True)
    ck = str(tmp_path / "cp.ckpt")
    mesh = make_mesh(8)
    straight = DDDShardEngine(cfg, mesh, caps).check()
    DDDShardEngine(cfg, mesh, caps).check(checkpoint=ck,
                                          checkpoint_every_s=0.0)
    resumed = DDDShardEngine(cfg, mesh, caps).check(resume=ck)
    assert resumed.n_states == straight.n_states
    assert resumed.levels == straight.levels
    # a dense-mode engine must refuse a CP snapshot (order differs)
    dense = dataclasses.replace(caps, cp=False)
    with pytest.raises(ValueError, match="digest|different model"):
        DDDShardEngine(cfg, mesh, dense).check(resume=ck)


def test_full_spec_small_parity_8dev():
    cfg = CheckConfig(bounds=Bounds(n_servers=2, n_values=1, max_term=2,
                                    max_log=1, max_msgs=2),
                      spec="full",
                      invariants=("NoTwoLeaders", "LogMatching",
                                  "CommittedWithinLog"),
                      chunk=128)
    ref = refbfs.check(cfg)
    caps = DDDShardCapacities(block=1 << 12, table=1 << 14,
                              seg_rows=1 << 15, flush=1 << 12, levels=64)
    got = DDDShardEngine(cfg, make_mesh(8), caps).check()
    assert_totals(got, ref)
    for fam in (S.RESTART, S.DUPLICATE, S.DROP):
        assert got.coverage[fam] > 0


def test_sigint_window_boundary_stop_and_resume(tmp_path):
    """ROADMAP item 8 leftover, chaos-tested in-process: the graceful
    SIGINT contract now reaches the ddd-shard child.  The flag is
    tripped mid-run (exactly what the installed handler does on the
    first Ctrl-C); the engine must stop at the next WINDOW boundary —
    the only point where the canonical shard-major stream is whole —
    snapshot there, return complete=False with no phantom violation,
    and the resumed run must land byte-identical to the uninterrupted
    one (states, levels, transitions, diameter, coverage)."""
    caps = DDDShardCapacities(block=32, table=1 << 14, seg_rows=1 << 14,
                              flush=1 << 10, levels=64)
    mesh = make_mesh(8)
    straight = DDDShardEngine(CFG, mesh, caps).check()
    ck = str(tmp_path / "sig.ck")
    eng = DDDShardEngine(CFG, mesh, caps)
    fired = {}

    def chaos(snap):
        if snap["n_states"] > 300 and not fired:
            fired["at"] = snap["n_states"]
            eng._sigint = True        # what the first SIGINT sets

    partial = eng.check(on_progress=chaos, checkpoint=ck,
                        checkpoint_every_s=1e9)
    assert fired, "chaos hook never fired — model too small"
    assert partial.complete is False
    assert partial.violation is None
    assert partial.n_states < straight.n_states
    resumed = DDDShardEngine(CFG, mesh, caps).check(resume=ck)
    assert resumed.complete is True
    assert_totals(resumed, straight)
    assert resumed.coverage == straight.coverage
