"""Megakernel parity suite (ops/pallas_step vs the XLA step).

The megakernel stages the XLA step's own jaxpr through a grid-blocked
``pl.pallas_call`` (interpret mode on CPU), so these tests check the
staging machinery — constant routing, int32 boundary casts, row-block
padding, output reassembly — not a hand-kept twin.  Anchors:

- full-dict bit-identity (every key, every lane, dtypes included) on
  reachable chunks at |G| = 6, 24, 120, in parity AND faithful mode,
  composed with Value symmetry and VIEW folding;
- the same bit-identity under every orbit-scan variant the gates can
  select (full scan, prescan ladder, sig-prune) — the variants ride
  inside the staged program, so each combination is its own staging;
- row-block padding edges (B not a block multiple, block larger than B);
- a NumPy-oracle anchor: megakernel key lanes equal
  ``sym.py_orbit_fingerprint`` of the corresponding PyState successor;
- engine/serve-level gate parity on the 3014-state toy: counts,
  diameter, coverage, violation + deadlock verdicts and traces all
  identical with ``RAFT_TLA_MEGAKERNEL`` forced on, and serve bins
  split on the gate so lanes can never mix step variants.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.engine import DEADLOCK, Engine
from raft_tla_tpu.models import interp
from raft_tla_tpu.models import spec as S
from raft_tla_tpu.ops import kernels
from raft_tla_tpu.ops import msgbits as mb
from raft_tla_tpu.ops import pallas_step
from raft_tla_tpu.ops import symmetry as sym

pytestmark = pytest.mark.smoke

TOY_BOUNDS = Bounds(n_servers=2, n_values=1, max_term=2, max_log=0,
                    max_msgs=2)                      # 3014-state toy
B3 = Bounds(n_servers=3, n_values=2, max_term=2, max_log=1, max_msgs=2)
B4 = Bounds(n_servers=4, n_values=1, max_term=2, max_log=0, max_msgs=2)
B5 = Bounds(n_servers=5, n_values=1, max_term=2, max_log=0, max_msgs=2)
BH = Bounds(n_servers=2, n_values=2, max_term=2, max_log=1, max_msgs=2,
            history=True, max_elections=4)
BH3 = Bounds(n_servers=3, n_values=1, max_term=2, max_log=1, max_msgs=2,
             history=True, max_elections=4)

TOY = CheckConfig(bounds=TOY_BOUNDS, spec="election",
                  invariants=("NoTwoLeaders",), chunk=256)
TOY_SYM = CheckConfig(bounds=TOY_BOUNDS, spec="election",
                      invariants=("NoTwoLeaders",), symmetry=("Server",),
                      chunk=256)


def _reach_vecs(bounds, spec, depth=3, cap=96, lane_cap=60):
    """BFS-prefix bag of reachable states as packed device rows."""
    frontier = [interp.init_state(bounds)]
    seen = list(frontier)
    for _ in range(depth):
        nxt = []
        for s in frontier:
            nxt += [t for _i, t in interp.successors(s, bounds, spec=spec)]
        frontier = nxt[:lane_cap]
        seen += frontier
    rows = np.stack([interp.to_vec(s, bounds) for s in seen[:cap]])
    return jnp.asarray(rows, jnp.int32), seen[:cap]


def _assert_step_parity(bounds, spec, invariants, symmetry, view=None,
                        depth=3, cap=96, **mk_kwargs):
    vecs, _states = _reach_vecs(bounds, spec, depth, cap)
    xla = kernels.build_step(bounds, spec, invariants, symmetry, view,
                             megakernel=False)
    mega = pallas_step.build_step_megakernel(
        bounds, spec, invariants, symmetry, view, **mk_kwargs)
    a, b = xla(vecs), mega(vecs)
    assert set(a) == set(b)
    for k in sorted(a):
        assert a[k].dtype == b[k].dtype, (k, a[k].dtype, b[k].dtype)
        assert a[k].shape == b[k].shape, (k, a[k].shape, b[k].shape)
        assert bool(jnp.all(a[k] == b[k])), (k, bounds, symmetry)
    return b


# -- chunk-level bit-identity ------------------------------------------------

def test_toy_parity_no_symmetry():
    """The symmetry-free path (plain fingerprints, no orbit scan)."""
    _assert_step_parity(TOY_BOUNDS, "election", ("NoTwoLeaders",), (),
                        depth=5, cap=128)


def test_toy_parity_symmetry():
    _assert_step_parity(TOY_BOUNDS, "election", ("NoTwoLeaders",),
                        ("Server",), depth=5, cap=128)


@pytest.mark.parametrize("bounds,spec,invariants,axes", [
    (B3, "full", ("NoTwoLeaders", "LogMatching"), ("Server",)),   # |G|=6
    (B4, "election", ("NoTwoLeaders",), ("Server",)),             # |G|=24
    (B5, "election", ("NoTwoLeaders",), ("Server",)),             # |G|=120
], ids=["G6", "G24", "G120"])
def test_symmetry_suite_parity(bounds, spec, invariants, axes):
    _assert_step_parity(bounds, spec, invariants, axes, cap=64)


@pytest.mark.slow
def test_value_symmetry_parity():
    """Server x Value composition in parity mode (faithful-mode SV
    composition rides tier-1 via test_faithful_parity[hist-SV])."""
    _assert_step_parity(B3, "full", ("NoTwoLeaders",),
                        ("Server", "Value"), cap=48)             # |G|=12


@pytest.mark.parametrize("bounds,axes", [
    (BH, ("Server", "Value")),                                   # |G|=4
    pytest.param(BH3, ("Server",), marks=pytest.mark.slow),      # |G|=6
], ids=["hist-SV", "hist-S6"])
def test_faithful_parity(bounds, axes):
    """History mode: the expansion postlude (allLogs) and the faithful
    value-permutation LUTs ride the staged program too."""
    _assert_step_parity(bounds, "full", ("NoTwoLeaders",), axes, cap=32)


def test_view_parity():
    _assert_step_parity(B3, "election", ("NoTwoLeaders",), ("Server",),
                        view="deadvotes", cap=64)


@pytest.mark.parametrize("prescan,sigprune", [
    ("off", "off"),        # full scan
    ("on", "off"),         # prescan-grouped (block-local in the kernel)
    pytest.param("off", "on",      # sig-prune coset scan
                 marks=pytest.mark.slow),
    pytest.param("on", "on",       # composed
                 marks=pytest.mark.slow),
])
def test_orbit_variant_parity(monkeypatch, prescan, sigprune):
    """Each gate combination stages a different orbit phase into the
    kernel; every one must stay bit-identical to its XLA twin.  The
    sig-prune arms ride the slow tier (the coset-scan staging alone
    traces ~40 s under interpret mode); tier-1 keeps the full scan and
    the prescan ladder, and runs/megakernel_ab.py re-asserts pruned
    parity at two shapes under the production auto policy every A/B."""
    monkeypatch.setenv("RAFT_TLA_PRESCAN", prescan)
    monkeypatch.setenv("RAFT_TLA_SIGPRUNE", sigprune)
    _assert_step_parity(B3, "election", ("NoTwoLeaders",), ("Server",),
                        cap=32)


def test_block_padding_edges():
    """B not a multiple of the row block — the zero-row padding in the
    tail block must never leak into a real lane (grid of 2 at 50 rows)."""
    _assert_step_parity(TOY_BOUNDS, "election", ("NoTwoLeaders",),
                        ("Server",), depth=4, cap=50, block_rows=32)


@pytest.mark.slow
def test_block_larger_than_chunk():
    """A block larger than the whole chunk: Bp = one padded block."""
    _assert_step_parity(TOY_BOUNDS, "election", ("NoTwoLeaders",),
                        ("Server",), depth=4, cap=50, block_rows=256)


def test_oracle_anchor():
    """Megakernel key lanes equal the NumPy oracle's orbit key of the
    corresponding PyState successor (not just the XLA path's output)."""
    vecs, states = _reach_vecs(B3, "election", depth=2, cap=8)
    mega = pallas_step.build_step_megakernel(
        B3, "election", (), ("Server",))
    out = mega(vecs)
    table = S.action_table(B3, "election")
    checked = 0
    for b, s in enumerate(states[:4]):
        for idx, t in interp.successors(s, B3, table=table):
            hi, lo = sym.py_orbit_fingerprint(t, B3, ("Server",))
            assert bool(out["valid"][b, idx])
            assert int(out["fp_hi"][b, idx]) == hi
            assert int(out["fp_lo"][b, idx]) == lo
            checked += 1
    assert checked > 10


# -- gate plumbing -----------------------------------------------------------

def test_routed_step_refuses_megakernel():
    with pytest.raises(ValueError, match="does not compose"):
        kernels.build_step_routed(TOY_BOUNDS, "election", (), (),
                                  k_rows=64, megakernel=True)


def test_gate_env_resolution(monkeypatch):
    monkeypatch.delenv("RAFT_TLA_MEGAKERNEL", raising=False)
    assert not kernels._megakernel_enabled(TOY_BOUNDS, ())   # auto = OFF
    monkeypatch.setenv("RAFT_TLA_MEGAKERNEL", "on")
    assert kernels._megakernel_enabled(TOY_BOUNDS, ())
    monkeypatch.setenv("RAFT_TLA_MEGAKERNEL", "off")
    assert not kernels._megakernel_enabled(TOY_BOUNDS, ())


def test_bin_key_splits_on_gate(monkeypatch):
    """serve bins must never mix step variants across a gate flip."""
    from raft_tla_tpu.serve.batch import bin_key
    monkeypatch.setenv("RAFT_TLA_MEGAKERNEL", "off")
    off = bin_key(TOY)
    monkeypatch.setenv("RAFT_TLA_MEGAKERNEL", "on")
    on = bin_key(TOY)
    assert off != on
    assert ("megakernel", True) in on and ("megakernel", False) in off


def test_jitlint_covers_pallas_step():
    """The jit-hazard lint scans ops/ by default; the megakernel module
    must be in scope and clean."""
    import os
    from raft_tla_tpu.analysis import jitlint
    assert any(t.endswith("raft_tla_tpu/ops") or t == "raft_tla_tpu"
               for t in jitlint.DEFAULT_TARGETS)
    path = os.path.join(os.path.dirname(pallas_step.__file__),
                        "pallas_step.py")
    with open(path) as fh:
        findings = jitlint.lint_source(fh.read(), path)
    assert findings == []


# -- engine / serve parity on the 3014-state toy -----------------------------

def assert_counts_equal(res, ref):
    assert res.n_states == ref.n_states
    assert res.diameter == ref.diameter
    assert res.n_transitions == ref.n_transitions
    assert list(res.levels) == list(ref.levels)
    assert dict(res.coverage) == dict(ref.coverage)
    assert res.complete and ref.complete


def test_engine_gate_on_off_parity(monkeypatch):
    monkeypatch.setenv("RAFT_TLA_MEGAKERNEL", "off")
    ref_plain = Engine(TOY).check()
    ref_sym = Engine(TOY_SYM).check()
    monkeypatch.setenv("RAFT_TLA_MEGAKERNEL", "on")
    got_plain = Engine(TOY).check()
    got_sym = Engine(TOY_SYM).check()
    assert ref_plain.n_states == 3014
    assert_counts_equal(got_plain, ref_plain)
    assert_counts_equal(got_sym, ref_sym)
    assert got_sym.n_states < got_plain.n_states     # quotient held


def bag(*ms):
    return tuple(sorted((m, 1) for m in ms))


VB = Bounds(n_servers=3, n_values=1, max_term=3, max_log=0, max_msgs=4)
VIOL = CheckConfig(bounds=VB, spec="election",
                   invariants=("NaiveNoTwoLeaders",), chunk=256)
DEAD = CheckConfig(bounds=Bounds(n_servers=1, n_values=1, max_term=2,
                                 max_log=0, max_msgs=2),
                   spec="election", invariants=(), check_deadlock=True,
                   chunk=256)


def seeded_start():
    """Two steps from a NaiveNoTwoLeaders violation (engine-test seed)."""
    return interp.init_state(VB)._replace(
        role=(S.LEADER, S.FOLLOWER, S.CANDIDATE),
        term=(2, 3, 3), votedFor=(1, 3, 0),
        vGrant=(0b011, 0, 0b100), msgs=bag(mb.rv_response(3, 1, 1, 2)))


def test_engine_violation_and_deadlock_mask_parity(monkeypatch):
    """The ok/inv mask lanes drive verdicts: a violating and a
    deadlocking universe must reach the identical verdict AND trace
    through the megakernel path."""
    monkeypatch.setenv("RAFT_TLA_MEGAKERNEL", "off")
    ref_viol = Engine(VIOL).check(init_override=seeded_start())
    ref_dead = Engine(DEAD).check()
    monkeypatch.setenv("RAFT_TLA_MEGAKERNEL", "on")
    got_viol = Engine(VIOL).check(init_override=seeded_start())
    got_dead = Engine(DEAD).check()

    assert got_viol.violation is not None
    assert got_viol.violation.invariant == "NaiveNoTwoLeaders"
    assert got_viol.violation.trace == ref_viol.violation.trace
    assert got_viol.violation.state == ref_viol.violation.state

    assert got_dead.violation is not None
    assert got_dead.violation.invariant == DEADLOCK \
        == ref_dead.violation.invariant
    assert got_dead.violation.trace == ref_dead.violation.trace


def test_serve_lane_parity(monkeypatch):
    """Lane-packed dispatches through the megakernel: completing lanes
    stay byte-identical to solo runs."""
    from raft_tla_tpu.serve.batch import BatchExecutor
    monkeypatch.setenv("RAFT_TLA_MEGAKERNEL", "off")
    solo = Engine(TOY).check()
    solo_sym = Engine(TOY_SYM).check()
    monkeypatch.setenv("RAFT_TLA_MEGAKERNEL", "on")
    out = BatchExecutor(chunk=256).run(
        [("a", TOY), ("b", TOY), ("sym", TOY_SYM)])
    for jid in ("a", "b"):
        assert out[jid].status == "completed"
        assert_counts_equal(out[jid].result, solo)
    assert out["sym"].status == "completed"
    assert_counts_equal(out["sym"].result, solo_sym)
