"""A third, independently-derived interpreter of the reference spec.

Purpose (VERDICT r1 "what's weak" #8): every parity chain in this repo
bottoms out at ``raft_tla_tpu/models/interp.py`` — a shared misreading of
``raft.tla`` would pass every differential test.  This module is a second,
*separate* transcription of ``/root/reference/raft.tla`` written directly
from the spec text with a deliberately different representation (records
and frozensets rather than packed arrays; the message bag as a frozenset
of ``(record, count)`` pairs), used by ``tests/test_independent_oracle.py``
to cross-check BFS level counts and full-space sizes against the package's
oracle and engines.  It intentionally lives under ``tests/`` — it is a
test instrument, not a product code path, and nothing in the package may
import it.

Parity mode only: the history variables (``elections``/``allLogs``/
``voterLog``, raft.tla:39,44,77) and history-only message fields (``mlog``,
raft.tla:220-222,297-299) are omitted — the same state identity the
package's parity mode uses (SURVEY §7.0.3).

Every function cites the raft.tla lines it transcribes.
"""

from __future__ import annotations

import itertools
from typing import NamedTuple

FOLLOWER, CANDIDATE, LEADER = "F", "C", "L"
NIL = None


class RVReq(NamedTuple):                       # raft.tla:193-198
    mterm: int
    mlastLogTerm: int
    mlastLogIndex: int
    msource: int
    mdest: int


class RVResp(NamedTuple):                      # raft.tla:294-301 (no mlog)
    mterm: int
    mvoteGranted: bool
    msource: int
    mdest: int


class AEReq(NamedTuple):                       # raft.tla:215-225 (no mlog)
    mterm: int
    mprevLogIndex: int
    mprevLogTerm: int
    mentries: tuple                            # () or ((term, value),)
    mcommitIndex: int
    msource: int
    mdest: int


class AEResp(NamedTuple):                      # raft.tla:338-343,366-372
    mterm: int
    msuccess: bool
    mmatchIndex: int
    msource: int
    mdest: int


class State(NamedTuple):
    """One global state, parity identity.  Per-server values are tuples
    indexed by server id 0..n-1; ``messages`` is the bag as a frozenset of
    ``(record, count)`` pairs (a function Message -> Nat, raft.tla:32)."""

    currentTerm: tuple
    role: tuple                                # 'state' in the spec
    votedFor: tuple                            # server id or NIL
    log: tuple                                 # per server: tuple of (term, value)
    commitIndex: tuple
    votesResponded: tuple                      # per server: frozenset of ids
    votesGranted: tuple
    nextIndex: tuple                           # per server: tuple over peers
    matchIndex: tuple
    messages: frozenset


def init_state(n: int) -> State:               # raft.tla:140-160
    return State(
        currentTerm=(1,) * n,
        role=(FOLLOWER,) * n,
        votedFor=(NIL,) * n,
        log=((),) * n,
        commitIndex=(0,) * n,
        votesResponded=(frozenset(),) * n,
        votesGranted=(frozenset(),) * n,
        nextIndex=((1,) * n,) * n,
        matchIndex=((0,) * n,) * n,
        messages=frozenset(),
    )


# -- bag helpers (raft.tla:106-130) -----------------------------------------

def with_message(m, msgs: frozenset) -> frozenset:     # raft.tla:106-110
    d = dict(msgs)
    d[m] = d.get(m, 0) + 1
    return frozenset(d.items())


def without_message(m, msgs: frozenset) -> frozenset:  # raft.tla:114-119
    d = dict(msgs)
    if m in d:
        if d[m] <= 1:
            del d[m]
        else:
            d[m] -= 1
    return frozenset(d.items())


def reply(resp, req, msgs: frozenset) -> frozenset:    # raft.tla:129-130
    return without_message(req, with_message(resp, msgs))


def last_term(xlog: tuple) -> int:                     # raft.tla:102
    return xlog[-1][0] if xlog else 0


def is_quorum(s: frozenset, n: int) -> bool:           # raft.tla:99
    return 2 * len(s) > n


def _upd(t: tuple, i: int, v):
    return t[:i] + (v,) + t[i + 1:]


# -- actions (raft.tla:165-276) ---------------------------------------------

def restart(s: State, i: int) -> State:                # raft.tla:167-175
    n = len(s.currentTerm)
    return s._replace(
        role=_upd(s.role, i, FOLLOWER),
        votesResponded=_upd(s.votesResponded, i, frozenset()),
        votesGranted=_upd(s.votesGranted, i, frozenset()),
        nextIndex=_upd(s.nextIndex, i, (1,) * n),
        matchIndex=_upd(s.matchIndex, i, (0,) * n),
        commitIndex=_upd(s.commitIndex, i, 0),
    )


def timeout(s: State, i: int):                         # raft.tla:178-187
    if s.role[i] not in (FOLLOWER, CANDIDATE):
        return None
    return s._replace(
        role=_upd(s.role, i, CANDIDATE),
        currentTerm=_upd(s.currentTerm, i, s.currentTerm[i] + 1),
        votedFor=_upd(s.votedFor, i, NIL),
        votesResponded=_upd(s.votesResponded, i, frozenset()),
        votesGranted=_upd(s.votesGranted, i, frozenset()),
    )


def request_vote(s: State, i: int, j: int):            # raft.tla:190-199
    if s.role[i] != CANDIDATE or j in s.votesResponded[i]:
        return None
    m = RVReq(mterm=s.currentTerm[i], mlastLogTerm=last_term(s.log[i]),
              mlastLogIndex=len(s.log[i]), msource=i, mdest=j)
    return s._replace(messages=with_message(m, s.messages))


def append_entries(s: State, i: int, j: int):          # raft.tla:204-226
    if i == j or s.role[i] != LEADER:
        return None
    prev_idx = s.nextIndex[i][j] - 1
    prev_term = s.log[i][prev_idx - 1][0] if prev_idx > 0 else 0
    last_entry = min(len(s.log[i]), s.nextIndex[i][j])
    # SubSeq(log, nextIndex, lastEntry), 1-based inclusive (raft.tla:214)
    entries = tuple(s.log[i][s.nextIndex[i][j] - 1:last_entry])
    m = AEReq(mterm=s.currentTerm[i], mprevLogIndex=prev_idx,
              mprevLogTerm=prev_term, mentries=entries,
              mcommitIndex=min(s.commitIndex[i], last_entry),
              msource=i, mdest=j)
    return s._replace(messages=with_message(m, s.messages))


def become_leader(s: State, i: int):                   # raft.tla:229-243
    n = len(s.currentTerm)
    if s.role[i] != CANDIDATE or not is_quorum(s.votesGranted[i], n):
        return None
    return s._replace(
        role=_upd(s.role, i, LEADER),
        nextIndex=_upd(s.nextIndex, i, (len(s.log[i]) + 1,) * n),
        matchIndex=_upd(s.matchIndex, i, (0,) * n),
    )


def client_request(s: State, i: int, v: int):          # raft.tla:246-253
    if s.role[i] != LEADER:
        return None
    entry = (s.currentTerm[i], v)
    return s._replace(log=_upd(s.log, i, s.log[i] + (entry,)))


def advance_commit_index(s: State, i: int):            # raft.tla:259-276
    if s.role[i] != LEADER:
        return None
    n = len(s.currentTerm)

    def agree(index):                                  # raft.tla:262-263
        return frozenset({i} | {k for k in range(n)
                                if s.matchIndex[i][k] >= index})
    agree_indexes = [x for x in range(1, len(s.log[i]) + 1)
                     if is_quorum(agree(x), n)]
    if agree_indexes and \
            s.log[i][max(agree_indexes) - 1][0] == s.currentTerm[i]:
        new_ci = max(agree_indexes)                    # raft.tla:268-272
    else:
        new_ci = s.commitIndex[i]
    return s._replace(commitIndex=_upd(s.commitIndex, i, new_ci))


# -- message handlers (raft.tla:282-436) ------------------------------------

def receive(s: State, m) -> list:
    """All enabled ``Receive(m)`` outcomes (raft.tla:421-436).  The guards
    partition on mterm vs currentTerm[i], so at most one disjunct fires."""
    i, j = m.mdest, m.msource
    if m.mterm > s.currentTerm[i]:                     # UpdateTerm, 406-412
        return [s._replace(                            # message NOT consumed
            currentTerm=_upd(s.currentTerm, i, m.mterm),
            role=_upd(s.role, i, FOLLOWER),
            votedFor=_upd(s.votedFor, i, NIL))]
    if isinstance(m, RVReq):
        return _handle_rv_req(s, i, j, m)
    if isinstance(m, RVResp):
        if m.mterm < s.currentTerm[i]:                 # DropStale, 415-418
            return [s._replace(messages=without_message(m, s.messages))]
        return _handle_rv_resp(s, i, j, m)
    if isinstance(m, AEReq):
        return _handle_ae_req(s, i, j, m)
    if isinstance(m, AEResp):
        if m.mterm < s.currentTerm[i]:                 # DropStale, 415-418
            return [s._replace(messages=without_message(m, s.messages))]
        return _handle_ae_resp(s, i, j, m)
    raise TypeError(m)


def _handle_rv_req(s, i, j, m):                        # raft.tla:284-303
    # here m.mterm <= currentTerm[i] holds (UpdateTerm took the > case)
    log_ok = (m.mlastLogTerm > last_term(s.log[i])
              or (m.mlastLogTerm == last_term(s.log[i])
                  and m.mlastLogIndex >= len(s.log[i])))
    grant = (m.mterm == s.currentTerm[i] and log_ok
             and s.votedFor[i] in (NIL, j))
    resp = RVResp(mterm=s.currentTerm[i], mvoteGranted=grant,
                  msource=i, mdest=j)
    out = s._replace(messages=reply(resp, m, s.messages))
    if grant:
        out = out._replace(votedFor=_upd(out.votedFor, i, j))
    return [out]


def _handle_rv_resp(s, i, j, m):                       # raft.tla:307-321
    if m.mterm != s.currentTerm[i]:
        return []
    out = s._replace(
        votesResponded=_upd(s.votesResponded, i,
                            s.votesResponded[i] | {j}),
        messages=without_message(m, s.messages))
    if m.mvoteGranted:
        out = out._replace(
            votesGranted=_upd(out.votesGranted, i,
                              s.votesGranted[i] | {j}))
    return [out]


def _handle_ae_req(s, i, j, m):                        # raft.tla:327-389
    # here m.mterm <= currentTerm[i]
    log_ok = (m.mprevLogIndex == 0
              or (0 < m.mprevLogIndex <= len(s.log[i])
                  and m.mprevLogTerm == s.log[i][m.mprevLogIndex - 1][0]))
    outs = []
    if (m.mterm < s.currentTerm[i]
            or (m.mterm == s.currentTerm[i] and s.role[i] == FOLLOWER
                and not log_ok)):                      # reject, 333-345
        resp = AEResp(mterm=s.currentTerm[i], msuccess=False,
                      mmatchIndex=0, msource=i, mdest=j)
        outs.append(s._replace(messages=reply(resp, m, s.messages)))
    if m.mterm == s.currentTerm[i] and s.role[i] == CANDIDATE:
        # return to follower state, message kept (346-350)
        outs.append(s._replace(role=_upd(s.role, i, FOLLOWER)))
    if m.mterm == s.currentTerm[i] and s.role[i] == FOLLOWER and log_ok:
        index = m.mprevLogIndex + 1                    # accept, 351-388
        if (m.mentries == ()
                or (len(s.log[i]) >= index
                    and s.log[i][index - 1][0] == m.mentries[0][0])):
            # already done with request (356-374); commitIndex may decrease
            resp = AEResp(mterm=s.currentTerm[i], msuccess=True,
                          mmatchIndex=m.mprevLogIndex + len(m.mentries),
                          msource=i, mdest=j)
            outs.append(s._replace(
                commitIndex=_upd(s.commitIndex, i, m.mcommitIndex),
                messages=reply(resp, m, s.messages)))
        if (m.mentries != () and len(s.log[i]) >= index
                and s.log[i][index - 1][0] != m.mentries[0][0]):
            # conflict: drop the LAST entry, message kept (375-382)
            outs.append(s._replace(log=_upd(s.log, i, s.log[i][:-1])))
        if m.mentries != () and len(s.log[i]) == m.mprevLogIndex:
            # no conflict: append entry, message kept (383-388)
            outs.append(s._replace(
                log=_upd(s.log, i, s.log[i] + (m.mentries[0],))))
    return outs


def _handle_ae_resp(s, i, j, m):                       # raft.tla:393-403
    if m.mterm != s.currentTerm[i]:
        return []
    if m.msuccess:
        ni = _upd(s.nextIndex[i], j, m.mmatchIndex + 1)
        mi = _upd(s.matchIndex[i], j, m.mmatchIndex)
        out = s._replace(nextIndex=_upd(s.nextIndex, i, ni),
                         matchIndex=_upd(s.matchIndex, i, mi))
    else:
        ni = _upd(s.nextIndex[i], j, max(s.nextIndex[i][j] - 1, 1))
        out = s._replace(nextIndex=_upd(s.nextIndex, i, ni))
    return [out._replace(messages=without_message(m, out.messages))]


# -- Next (raft.tla:454-465) and bounded BFS --------------------------------

def successors(s: State, n: int, values: int) -> list:
    """Every state reachable in one ``Next`` step (parity identity)."""
    out = []
    for i in range(n):
        out.append(restart(s, i))                      # raft.tla:454
        out.append(timeout(s, i))                      # raft.tla:455
        for j in range(n):
            out.append(request_vote(s, i, j))          # raft.tla:456
            out.append(append_entries(s, i, j))        # raft.tla:460
        out.append(become_leader(s, i))                # raft.tla:457
        for v in range(1, values + 1):
            out.append(client_request(s, i, v))        # raft.tla:458
        out.append(advance_commit_index(s, i))         # raft.tla:459
    for m, _count in s.messages:                       # raft.tla:461-463
        out.extend(receive(s, m))
        out.append(s._replace(messages=with_message(m, s.messages)))
        out.append(s._replace(messages=without_message(m, s.messages)))
    return [t for t in out if t is not None]


def constraint_ok(s: State, max_term: int, max_log: int, max_msgs: int,
                  max_dup: int) -> bool:
    """The StateConstraint (SURVEY §0 defect 2) — same bound the package
    enforces via its tensor encoding."""
    return (all(t <= max_term for t in s.currentTerm)
            and all(len(lg) <= max_log for lg in s.log)
            and len(s.messages) <= max_msgs
            and all(c <= max_dup for _m, c in s.messages))


def bfs(n: int, values: int, max_term: int, max_log: int, max_msgs: int,
        max_dup: int = 1, max_levels: int | None = None) -> list:
    """Exhaustive bounded BFS; returns per-level new-state counts.
    Constraint-violating states are discovered and counted but never
    expanded (TLC CONSTRAINT semantics)."""
    init = init_state(n)
    seen = {init}
    frontier = [init]
    levels = [1]
    while frontier and (max_levels is None or len(levels) <= max_levels):
        nxt = []
        for s in frontier:
            if not constraint_ok(s, max_term, max_log, max_msgs, max_dup):
                continue
            for t in successors(s, n, values):
                if t not in seen:
                    seen.add(t)
                    nxt.append(t)
        if nxt:
            levels.append(len(nxt))
        frontier = nxt
    return levels
