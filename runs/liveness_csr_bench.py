"""CSR fast-path timing on the r3 2.05M-state liveness graph (election
3s t2/m2): graph export once, then each verdict through the new
_check_csr (C++ Tarjan + vectorized reach/stutter) vs the r3-recorded
list-path times (EventuallyLeader WF(Next) 25 s, stutter 16 s,
InfinitelyOftenLeader 58 s — runs/liveness_2m.out)."""
import json, os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
jax.config.update("jax_platforms", "cpu")
from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.ddd_engine import DDDCapacities
from raft_tla_tpu.models import liveness

CFG = CheckConfig(
    bounds=Bounds(n_servers=3, n_values=2, max_term=2, max_log=0,
                  max_msgs=2, max_dup=1),
    spec="election", invariants=(), chunk=1024)
t0 = time.time()
g = liveness.ddd_graph(CFG, DDDCapacities(block=1 << 16, table=1 << 20,
                                          seg_rows=1 << 17,
                                          flush=1 << 18, levels=256))
print(json.dumps({"phase": "graph", "states": len(g[0]),
                  "edges": g[1].n_edges,
                  "wall_s": round(time.time() - t0, 1)}), flush=True)
for prop, wf in (("EventuallyLeader", ("Next",)),
                 ("EventuallyLeader", ()),
                 ("InfinitelyOftenLeader", ("Next",))):
    t1 = time.time()
    r = liveness.check(CFG, prop, wf=wf, graph=g)
    print(json.dumps({"prop": prop, "wf": list(wf), "holds": r.holds,
                      "wall_s": round(time.time() - t1, 2),
                      "n_sccs_checked": r.n_sccs_checked}), flush=True)
g[0].close()
