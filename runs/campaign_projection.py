"""Live projection for the elect5 campaign (BASELINE config #2).

Reads runs/elect5ddd.stats (the live run) and runs/elect5ddd_r4_final.stats
(the round-4 record: exact per-level orbit counts through L30 complete +
L31 partial), prints the current incremental rate, the pace ratio vs the
r4 run at the same cumulative count, and a completion projection for a
given stop deadline.

Usage: python runs/campaign_projection.py [stop_utc_HH:MM] [STATS_PATH]

STATS_PATH (any argument without a ':') is the live stats stream —
either a v1 event log (--events) or a legacy .stats stream — to project
from; default runs/elect5ddd.stats.

Thin client of raft_tla_tpu.obs.monitor: all parsing (resume wall
rebasing, checkpoint-rollback dropping, legacy-line lifting) lives
there; this script keeps only the campaign-specific projection math
(pace vs the r4 record, landmarks, stop-deadline budget).
"""
import datetime
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from raft_tla_tpu.obs.monitor import load_stream

RUNS = os.path.dirname(os.path.abspath(__file__))


def load(name):
    """Segments of an event log or legacy stats stream, on the
    cumulative (resume-rebased, rollback-dropped) clock."""
    path = name if os.path.sep in name else os.path.join(RUNS, name)
    segs = load_stream(path)["segments"]
    return [dict(d, wall_s=d["cum_wall_s"]) for d in segs]


def main():
    paths = [a for a in sys.argv[1:] if ":" not in a]
    live = load(paths[0] if paths else "elect5ddd.stats")
    r4 = load("elect5ddd_r4_final.stats")
    if not live:
        sys.exit("no live stats yet")
    cur = live[-1]
    n, w, lv = cur["n_states"], cur["wall_s"], cur["level"]

    # incremental rate over the last ~10 min of flushes
    tail = [d for d in live if d["wall_s"] >= w - 600 and d["wall_s"] <= w]
    if len(tail) >= 2:
        inc = (tail[-1]["n_states"] - tail[0]["n_states"]) / max(
            1e-9, tail[-1]["wall_s"] - tail[0]["wall_s"])
    else:
        inc = cur.get("inc_states_per_sec", 0.0)

    # r4 wall at the same cumulative count (linear within flushes)
    r4_wall = None
    for a, b in zip(r4, r4[1:]):
        if a["n_states"] <= n <= b["n_states"]:
            f = (n - a["n_states"]) / max(1, b["n_states"] - a["n_states"])
            r4_wall = a["wall_s"] + f * (b["wall_s"] - a["wall_s"])
            break
    pace = (r4_wall / w) if r4_wall else None

    # known space landmarks from r4
    r4_end_states = 983_412_637          # L31 partial endpoint
    lv_sizes = {}
    seen = {}
    for d in r4:
        seen[d["level"]] = d["n_states"]
    ks = sorted(seen)
    for i, k in enumerate(ks[1:], 1):
        lv_sizes[k] = seen[k] - seen[ks[i - 1]]

    print(f"now: L{lv}, {n:,} orbits, wall {w:,.0f}s, "
          f"inc {inc:,.0f}/s" + (f", pace vs r4 {pace:.2f}x" if pace else ""))
    print(f"r4 endpoint {r4_end_states:,} (L30 complete; L31 partial "
          f"+83.4M; L30 size {lv_sizes.get(30, 0):,})")

    stops = [a for a in sys.argv[1:] if ":" in a]
    if stops:
        hh, mm = map(int, stops[0].split(":"))
        now = datetime.datetime.now(datetime.timezone.utc)
        stop = now.replace(hour=hh, minute=mm, second=0, microsecond=0)
        if stop < now:
            stop += datetime.timedelta(days=1)
        left = (stop - now).total_seconds()
        print(f"budget to {stops[0]}Z: {left / 3600:.2f}h -> "
              f"+{inc * left:,.0f} orbits at the current rate "
              f"(endpoint ~{n + inc * left:,.0f})")


if __name__ == "__main__":
    main()
