"""Incremental rates from a --stats JSONL stream.

The ``states_per_sec`` field in engine stats is CUMULATIVE (n_states /
own wall clock), which inflates arbitrarily after a checkpoint resume —
round 2's "164k -> 84k decay" was this artifact (RESULTS.md "an honesty
correction").  This tool prints the true incremental rate between
consecutive lines, plus per-level summaries.

Usage:  python runs/stats_rate.py runs/elect5ddd.stats [--tail N]
"""

import json
import sys


def main() -> None:
    path = sys.argv[1]
    tail = int(sys.argv[sys.argv.index("--tail") + 1]) \
        if "--tail" in sys.argv else 20
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    out = []
    for a, b in zip(rows, rows[1:]):
        dw = b["wall_s"] - a["wall_s"]
        ds = b["n_states"] - a["n_states"]
        if dw <= 0:
            # wall clock restarted: a resume boundary, not a rate
            out.append({"resume_boundary": True,
                        "n_states": b["n_states"]})
            continue
        out.append({
            "wall_s": round(b["wall_s"], 1),
            "level": b.get("level"),
            "n_states": b["n_states"],
            "inc_states_per_sec": round(ds / dw, 1),
            "cumulative_field_said": b.get("states_per_sec"),
        })
    for r in out[-tail:]:
        print(json.dumps(r))


if __name__ == "__main__":
    main()
