"""BASELINE config #5 liveness at 4 servers (VERDICT r3 next #6).

EventuallyLeader under weak fairness on the 4-server election sub-spec
(the 5-server quotient measured past the exact checker's practical
bound — runs/liveness5_probe.out and RESULTS.md; this is the deepest
server count the graph checker takes whole),
tightly bounded (t2/m1), through models/liveness.ddd_graph with
SYMMETRY Server — the orbit-quotient fair-lasso check at |G| = 4! = 24
(the exactness argument in ddd_graph's docstring: the registered
predicates are permutation-invariant, WF is per permutation-closed
family, and fair lassos project/lift through the quotient).

Also records the no-fairness verdict (the reference Spec's actual
situation, raft.tla:469: stuttering refutes every eventuality) as the
control.  CPU backend — set JAX_PLATFORMS=cpu via jax.config before
anything touches the device (the axon sitecustomize wins otherwise).

Writes one JSON line per verdict to stdout and appends to
runs/liveness_4s.out.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.ddd_engine import DDDCapacities
from raft_tla_tpu.models import liveness

CFG = CheckConfig(
    bounds=Bounds(n_servers=4, n_values=1, max_term=2, max_log=0,
                  max_msgs=1, max_dup=1),
    spec="election", invariants=(), symmetry=("Server",), chunk=1024)

CAPS = DDDCapacities(block=1 << 16, table=1 << 20, seg_rows=1 << 17,
                     flush=1 << 18, levels=256)


def main() -> None:
    t0 = time.time()
    graph = liveness.ddd_graph(CFG, CAPS)
    n = len(graph[0])
    n_edges = graph[1].n_edges
    print(json.dumps({"phase": "graph", "orbits": n, "edges": n_edges,
                      "wall_s": round(time.time() - t0, 1)}), flush=True)
    for prop, wf in (("EventuallyLeader", ("Next",)),
                     ("EventuallyLeader", ()),
                     ("EventuallyLeader", ("Timeout",)),
                     ("EventuallyLeader", ("Timeout", "RequestVote",
                                           "BecomeLeader", "Receive")),
                     ("InfinitelyOftenLeader", ("Next",)),
                     ("InfinitelyOftenLeader", ())):
        t1 = time.time()
        r = liveness.check(CFG, prop, wf=wf, graph=graph)
        print(json.dumps({
            "prop": prop, "wf": list(wf), "holds": r.holds,
            "n_states": r.n_states, "n_edges": r.n_edges,
            "n_sccs_checked": r.n_sccs_checked,
            "wall_s": round(time.time() - t1, 1)}), flush=True)
    graph[0].close()


if __name__ == "__main__":
    main()
