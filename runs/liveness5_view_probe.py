"""VERDICT r4 missing #5: the 5-server liveness composition, measured.

The r4 probe (runs/liveness5_probe.out) measured the plain SYMMETRY
quotient at 5s/t2/m1: 527k orbits by ~L20, still x2-3 per level —
infeasible for the exact graph checker.  This composes the deadvotes
VIEW (exact bisimulation, liveness-sound since round 5) on top of
SYMMETRY and measures the level growth it actually buys, same bounds,
same deadline protocol.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

if "--tpu" not in sys.argv:
    jax.config.update("jax_platforms", "cpu")

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine

CFG = CheckConfig(
    bounds=Bounds(n_servers=5, n_values=2, max_term=2, max_log=0,
                  max_msgs=1, max_dup=1),
    spec="election", invariants=(), symmetry=("Server",),
    view="deadvotes", chunk=1024)

deadline = float(sys.argv[1]) if len(sys.argv) > 1 and \
    not sys.argv[1].startswith("--") else 1200.0
eng = DDDEngine(CFG, DDDCapacities(block=1 << 16, table=1 << 20,
                                   seg_rows=1 << 17, flush=1 << 18,
                                   levels=256, retention="frontier"))
r = eng.check(deadline_s=deadline,
              on_progress=lambda s: print(json.dumps(
                  {k: s[k] for k in ("wall_s", "n_states", "level")}),
                  flush=True))
print(json.dumps({"final": r.n_states, "levels": r.levels,
                  "complete": r.complete, "wall_s": round(r.wall_s, 1)}))
