"""VERDICT r4 weak #2 / next #3: post-filter step anatomy.

The non-filter ~90% of the per-chunk step (31.3 ms of 34.5 ms at
flagship shapes, runs/filter_inengine.out "none" ablation) has had no
breakdown since round 2.  This ablates the fused step at the flagship
shape (3s/2v t2/l1/m2, SYMMETRY Server, chunk 4096) by DCE-fetching
output subsets and by rebuilding with stages removed:

  full        every output fetched (the engine's real program)
  no-inv      invariants=() rebuild           -> invariant-lane share
  fp-only     fetch (valid, fp) only          -> svecs-pack share (DCE)
  valid-only  fetch valid only                -> fingerprint+canon share
  no-sym      symmetry=() rebuild, fetch all  -> orbit-scan share
              (counts differ — this is a COST ablation, not a
              semantics-preserving variant)

Protocol: sync timing (block_until_ready between reps — the r3/r4
measured trap: async-loop timing amortizes the ~112 ms tunnel dispatch
floor and lies about in-engine cost), median of reps, one warmup
compile per variant.  Run on CPU for a relative baseline, on the chip
(--tpu) for the authoritative shares.

Usage: python runs/step_anatomy.py [--tpu] [reps]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

if "--tpu" not in sys.argv:
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.models import interp
from raft_tla_tpu.ops import kernels

REPS = next((int(a) for a in sys.argv[1:] if a.isdigit()), 30)
B = 4096
BOUNDS = Bounds(n_servers=3, n_values=2, max_term=2, max_log=1,
                max_msgs=2, max_dup=1)
INVS = ("NoTwoLeaders", "LogMatching", "CommittedWithinLog",
        "LeaderCompleteness")

# a mid-depth-looking chunk: replicate init then advance a few times so
# rows are non-trivial (bags populated) — identical inputs per variant
init = interp.init_state(BOUNDS)
frontier = [init]
seen = {init}
pool = []
while len(pool) < B:        # B DISTINCT rows — a cycled pool inflates
    if not frontier:        # the in-chunk duplicate share
        raise SystemExit(
            f"space exhausted below {B} distinct rows per level — "
            "shrink B or widen BOUNDS")
    nxt = []
    for s in frontier:
        if not interp.constraint_ok(s, BOUNDS):
            continue
        for _i, t in interp.successors(s, BOUNDS, spec="full"):
            if t not in seen:
                seen.add(t)
                nxt.append(t)
    frontier = nxt
    pool = [s for s in frontier if interp.constraint_ok(s, BOUNDS)]
rows = np.stack([interp.to_vec(s, BOUNDS) for s in pool[:B]])
vecs = jnp.asarray(rows)

VARIANTS = {}


def _add(name, invs, symmetry, keys):
    raw = kernels.build_step(BOUNDS, "full", invs, symmetry)
    if keys is None:
        fn = jax.jit(raw)
    else:
        fn = jax.jit(lambda v, _r=raw, _k=keys: {k: _r(v)[k]
                                                 for k in _k})
    VARIANTS[name] = fn


_add("full", INVS, ("Server",), None)
_add("no-inv", (), ("Server",), None)
_add("fp-only", (), ("Server",), ("valid", "fp_hi", "fp_lo"))
_add("valid-only", (), ("Server",), ("valid",))
_add("no-sym", INVS, (), None)

out = {}
for name, fn in VARIANTS.items():
    r = fn(vecs)
    jax.block_until_ready(r)            # compile + warm
    times = []
    for _ in range(REPS):
        t0 = time.monotonic()
        jax.block_until_ready(fn(vecs))
        times.append(time.monotonic() - t0)
    med = sorted(times)[len(times) // 2]
    out[name] = med
    print(f"{name:11} {med * 1e3:8.2f} ms/chunk "
          f"({B / med:9,.0f} rows/s)", flush=True)

full = out["full"]
print(json.dumps({
    "platform": jax.devices()[0].platform, "chunk": B, "reps": REPS,
    "ms_full": round(full * 1e3, 2),
    "share_invariants": round(1 - out["no-inv"] / full, 3),
    "share_svecs_pack": round((out["no-inv"] - out["fp-only"]) / full, 3),
    "share_fp_canon": round((out["fp-only"] - out["valid-only"]) / full,
                            3),
    "share_orbit_scan_vs_nosym": round(1 - out["no-sym"] / full, 3),
    "share_expand_residual": round(out["valid-only"] / full, 3),
}))
