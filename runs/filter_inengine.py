"""VERDICT r3 item #1 closure gate: IN-ENGINE ablation of the DDD
filter redesign (not standalone, not synthetic — the protocol from
runs/filter_anatomy.py / RESULTS.md "measurement protocol").

Times the REAL jitted segment program (ddd_engine._build_segment — the
while_loop the campaigns run) over a 16-chunk constraint-clean frontier
block at flagship shapes, with the module filter swapped between:

- ``new``      — the round-4 compacted-insert filter (same two-table
                 layout and probe, argsort-compacted S=16k-update
                 scatters; ddd_engine._filter_insert as shipped.  A
                 combined [TB, BUCKET, 2] single-table variant was
                 measured 1.6x SLOWER in-engine — rank-3 minor-dim-2
                 layout wrecks the probe gather — and rejected);
- ``old2d``    — the rounds-1-3 design: two [TB, BUCKET] tables, full-N
                 2-D element scatters (reconstructed here verbatim);
- ``none``     — in-batch first-of-key only, no table (the filter's
                 lower bound; streams every cross-chunk re-sight).

Reports per-chunk device ms (sync timing minus the measured dispatch
floor) and the filter's share of the step.  The r3 G-probe bug (rows
past the state constraint fed with fcon=1 -> FAIL_WIDTH after chunk 0)
is fixed by keeping only constraint-ok states in the frontier.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import raft_tla_tpu.ddd_engine as dddm
from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine
from raft_tla_tpu.device_engine import _EMPTY, BUCKET
from raft_tla_tpu.models import interp, spec as S

from filter_ablation import CFG, TABLE

I32 = jnp.int32
U32 = jnp.uint32
N_CHUNKS = 16
FLOOR_MS = 112.0          # measured tunnel dispatch floor (filter_anatomy)


def frontier_rows_con(n_rows: int) -> np.ndarray:
    """Constraint-OK frontier states only (the engine never expands
    constraint violators — feeding them with fcon=1 was the r3 bug)."""
    bounds = CFG.bounds
    init = interp.init_state(bounds)
    seen, frontier = {init}, [init]
    rows = [interp.to_vec(init, bounds)]
    while len(rows) < n_rows:
        nxt = []
        for s in frontier:
            if not interp.constraint_ok(s, bounds):
                continue
            for _i, t in interp.successors(s, bounds, spec=CFG.spec):
                if t not in seen:
                    seen.add(t)
                    nxt.append(t)
                    if interp.constraint_ok(t, bounds):
                        rows.append(interp.to_vec(t, bounds))
                        if len(rows) >= n_rows:
                            break
            if len(rows) >= n_rows:
                break
        frontier = nxt or frontier
    return np.asarray(rows[:n_rows], np.int32)


def filter_old2d(tbl_hi, tbl_lo, key_hi, key_lo, active):
    """The rounds-1-3 filter, verbatim: identical stream semantics,
    full-N 2-D element scatters on each word plane."""
    BA = key_hi.shape[0]
    TB, Sb = tbl_hi.shape
    bmask = jnp.uint32(TB - 1)
    skh = jnp.where(active, key_hi, _EMPTY)
    skl = jnp.where(active, key_lo, _EMPTY)
    perm = jnp.lexsort((skl, skh))
    ph, pl_, pa = key_hi[perm], key_lo[perm], active[perm]
    same_as_prev = jnp.concatenate([
        jnp.zeros((1,), bool),
        (ph[1:] == ph[:-1]) & (pl_[1:] == pl_[:-1]) & pa[1:] & pa[:-1]])
    first_of_key = jnp.zeros((BA,), bool).at[perm].set(~same_as_prev)
    probe = active & first_of_key
    bidx = (key_lo & bmask).astype(I32)
    row_hi, row_lo = tbl_hi[bidx], tbl_lo[bidx]
    seen = jnp.any((row_hi == key_hi[:, None])
                   & (row_lo == key_lo[:, None]), axis=1)
    stream = probe & ~seen
    slot_empty = (row_hi == _EMPTY) & (row_lo == _EMPTY)
    has_empty = jnp.any(slot_empty, axis=1)
    evict = (key_hi % jnp.uint32(Sb)).astype(I32)
    wslot = jnp.where(has_empty, jnp.argmax(slot_empty, axis=1), evict)
    wb = jnp.where(stream, bidx, TB)
    tbl_hi = tbl_hi.at[wb, wslot].set(key_hi, mode="drop")
    tbl_lo = tbl_lo.at[wb, wslot].set(key_lo, mode="drop")
    return tbl_hi, tbl_lo, stream


def filter_none(tbl_hi, tbl_lo, key_hi, key_lo, active):
    """In-batch first-of-key only — the no-table lower bound."""
    BA = key_hi.shape[0]
    skh = jnp.where(active, key_hi, _EMPTY)
    skl = jnp.where(active, key_lo, _EMPTY)
    perm = jnp.lexsort((skl, skh))
    ph, pl_, pa = key_hi[perm], key_lo[perm], active[perm]
    same_as_prev = jnp.concatenate([
        jnp.zeros((1,), bool),
        (ph[1:] == ph[:-1]) & (pl_[1:] == pl_[:-1]) & pa[1:] & pa[:-1]])
    first_of_key = jnp.zeros((BA,), bool).at[perm].set(~same_as_prev)
    return tbl_hi, tbl_lo, active & first_of_key


def filter_probeonly(tbl_hi, tbl_lo, key_hi, key_lo, active):
    """Probe + seen, NO insert — isolates the in-engine insert cost."""
    BA = key_hi.shape[0]
    TB, Sb = tbl_hi.shape
    bmask = jnp.uint32(TB - 1)
    skh = jnp.where(active, key_hi, _EMPTY)
    skl = jnp.where(active, key_lo, _EMPTY)
    perm = jnp.lexsort((skl, skh))
    ph, pl_, pa = key_hi[perm], key_lo[perm], active[perm]
    same_as_prev = jnp.concatenate([
        jnp.zeros((1,), bool),
        (ph[1:] == ph[:-1]) & (pl_[1:] == pl_[:-1]) & pa[1:] & pa[:-1]])
    first_of_key = jnp.zeros((BA,), bool).at[perm].set(~same_as_prev)
    probe = active & first_of_key
    bidx = (key_lo & bmask).astype(I32)
    row_hi, row_lo = tbl_hi[bidx], tbl_lo[bidx]
    seen = jnp.any((row_hi == key_hi[:, None])
                   & (row_lo == key_lo[:, None]), axis=1)
    return tbl_hi, tbl_lo, probe & ~seen


def main() -> None:
    B = CFG.chunk
    A = len(S.action_table(CFG.bounds, CFG.spec))
    rows = frontier_rows_con(B * N_CHUNKS)
    out = {"backend": jax.devices()[0].platform, "chunk": B, "lanes": A,
           "n_chunks": N_CHUNKS, "table_slots": TABLE}

    orig = dddm._filter_insert
    for name, filt, tbl_slots in (
            ("new", orig, TABLE), ("old2d", filter_old2d, TABLE),
            ("none", filter_none, TABLE),
            ("probeonly", filter_probeonly, TABLE),
            ("new_smalltbl", orig, 1 << 22),
            ("probeonly_smalltbl", filter_probeonly, 1 << 22)):
        dddm._filter_insert = filt
        eng = DDDEngine(CFG, DDDCapacities(
            block=B * N_CHUNKS, table=tbl_slots,
            seg_rows=B * A * N_CHUNKS))
        fbuf = jnp.asarray(eng.schema.pack(rows, np))
        fcon = jnp.ones((B * N_CHUNKS,), bool)

        def seg_once(fc, bufs):
            return eng._segment(fc, bufs, fbuf, fcon,
                                jnp.int32(N_CHUNKS), jnp.int32(0),
                                jnp.int32(B * N_CHUNKS))
        fc = eng._init_filter()
        bufs = eng._make_bufs()
        _, _, stats = jax.block_until_ready(seg_once(fc, bufs))
        res = {"chunks": int(stats.steps), "cursor": int(stats.cursor),
               "fail": int(stats.fail), "viol_kind": int(stats.viol_kind)}
        ts = []
        for _ in range(5):
            fc = eng._init_filter()
            bufs = eng._make_bufs()
            jax.block_until_ready((fc, bufs))
            t0 = time.perf_counter()
            _, _, statsx = seg_once(fc, bufs)
            jax.block_until_ready(statsx)
            ts.append(time.perf_counter() - t0)
        ms = float(np.median(ts)) * 1e3
        res["segment_sync_ms"] = round(ms, 3)
        res["per_chunk_ms"] = round(
            (ms - FLOOR_MS) / max(int(stats.steps), 1), 3)
        out[name] = res
        del eng

    dddm._filter_insert = orig
    new, old, none, ponly = (out[k]["per_chunk_ms"] for k in
                             ("new", "old2d", "none", "probeonly"))
    out["speedup_old_to_new"] = round(old / new, 3)
    out["filter_cost_new_ms"] = round(new - none, 3)
    out["filter_share_new"] = round((new - none) / new, 4)
    out["filter_cost_old_ms"] = round(old - none, 3)
    out["probe_cost_inengine_ms"] = round(ponly - none, 3)
    out["insert_cost_inengine_ms"] = round(new - ponly, 3)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
