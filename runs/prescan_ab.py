"""On-chip A/B of the pre-orbit raw-fp prescan (runs/step_anatomy.out
CPU-measured 2.22x) — decides whether the elect5 campaign runs with the
prescan ladder on or off.  The lexsort at the ladder's heart is a CPU
win but sorts are historically slow on TPU; bench_early_r5.json
(62.1k orbits/s vs the round-4 preview's 102.6k) suggests it inverts.

Builds the fused step at a given shape twice — the _prescan_enabled
gate forced True vs forced False (the harness measures the comparison
the gate encodes, so it must bypass the gate itself) — on identical
mid-depth distinct-row chunks, sync-timed (the r3/r4 protocol:
block_until_ready between reps, median of reps).

Usage: python runs/prescan_ab.py [--cpu] [flagship|elect5] [reps]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

if "--cpu" in sys.argv:
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.models import interp
from raft_tla_tpu.ops import kernels

SHAPE = "elect5" if "elect5" in sys.argv else "flagship"
REPS = next((int(a) for a in sys.argv[1:] if a.isdigit()), 30)
B = 4096
if SHAPE == "flagship":
    BOUNDS = Bounds(n_servers=3, n_values=2, max_term=2, max_log=1,
                    max_msgs=2, max_dup=1)
    SPEC, INVS = "full", ("NoTwoLeaders", "LogMatching",
                          "CommittedWithinLog", "LeaderCompleteness")
else:
    BOUNDS = Bounds(n_servers=5, n_values=2, max_term=2, max_log=0,
                    max_msgs=2, max_dup=1)
    SPEC, INVS = "election", ("NoTwoLeaders", "CommittedWithinLog")

init = interp.init_state(BOUNDS)
frontier, seen, pool = [init], {init}, []
while len(pool) < B:
    if not frontier:
        raise SystemExit(
            f"space exhausted below {B} distinct rows per level — "
            "shrink B or widen BOUNDS")
    nxt = []
    for s in frontier:
        if not interp.constraint_ok(s, BOUNDS):
            continue
        for _i, t in interp.successors(s, BOUNDS, spec=SPEC):
            if t not in seen:
                seen.add(t)
                nxt.append(t)
    frontier = nxt
    pool = [s for s in frontier if interp.constraint_ok(s, BOUNDS)]
rows = np.stack([interp.to_vec(s, BOUNDS) for s in pool[:B]])
vecs = jnp.asarray(rows)

out = {}
# force each arm PAST the _prescan_enabled platform/shape gate — the
# harness exists to measure the comparison the gate encodes, so it
# must not be subject to it
for name, gate in (("prescan", lambda *_: True),
                   ("off", lambda *_: False)):
    saved = kernels._prescan_enabled
    kernels._prescan_enabled = gate
    try:
        fn = jax.jit(kernels.build_step(BOUNDS, SPEC, INVS, ("Server",)))
        r = fn(vecs)
        jax.block_until_ready(r)
    finally:
        kernels._prescan_enabled = saved
    # parity across variants while we're here — same fps bit-for-bit
    if name == "prescan":
        ref_fp = (np.asarray(r["fp_hi"]), np.asarray(r["fp_lo"]))
    else:
        assert np.array_equal(np.asarray(r["fp_hi"]), ref_fp[0])
        assert np.array_equal(np.asarray(r["fp_lo"]), ref_fp[1])
    times = []
    for _ in range(REPS):
        t0 = time.monotonic()
        jax.block_until_ready(fn(vecs))
        times.append(time.monotonic() - t0)
    med = sorted(times)[len(times) // 2]
    out[name] = med
    print(f"{name:8} {med * 1e3:8.2f} ms/chunk ({B / med:9,.0f} rows/s)",
          flush=True)

print(json.dumps({
    "platform": jax.devices()[0].platform, "shape": SHAPE, "chunk": B,
    "reps": REPS, "ms_prescan": round(out["prescan"] * 1e3, 2),
    "ms_off": round(out["off"] * 1e3, 2),
    "speedup_from_prescan": round(out["off"] / out["prescan"], 3)}))
