"""Serve lane-packing A/B (ISSUE 6 acceptance gate).

Claim under test: packing a 16-job toy-universe manifest into the
lane-packed :class:`BatchExecutor` (a) leaves every lane's counts and
verdict byte-identical to a solo ``engine.Engine`` run of the same cfg,
and (b) delivers >= 80% of the summed solo aggregate throughput — the
batch pays one jit compile per *bin* (4 bins here) where the solo arm
pays one per *job* (16), and fills its shared chunk across tenants
where each solo run pads its own.

Protocol (the chip-state-fiducial discipline of RESULTS.md "sig-prune
A/B"): arms interleave round-robin so machine drift hits both equally,
and every rep carries a fiducial — a synthetic jitted step + 64 MB
device copy timed immediately before the arm — so a drifted rep is
visible in the artifact instead of silently biasing a mean.  Parity is
asserted on EVERY rep, not sampled.

Manifest: 16 jobs over 4 step-signature bins, all 2-server election
universes (the 3,014-state toy x8, its Server-symmetry quotient x4,
a max_term=3 widening x2, a max_msgs=3 widening x2).

Usage: python runs/serve_ab.py [reps]   (default 3)
Appends one JSON line per rep + a summary line to runs/serve_ab.out.
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.engine import Engine
from raft_tla_tpu.serve.batch import BatchExecutor, bin_key

RUNS = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(RUNS, "serve_ab.out")

CHUNK = 256                           # shared dispatch width, both arms


def _cfg(**kw):
    b = dict(n_servers=2, n_values=1, max_term=2, max_log=0, max_msgs=2)
    sym = kw.pop("symmetry", ())
    b.update(kw)
    return CheckConfig(bounds=Bounds(**b), spec="election",
                       invariants=("NoTwoLeaders",), symmetry=sym,
                       chunk=CHUNK)


TOY = _cfg()                          # 3,014 states, diameter 17
TOY_SYM = _cfg(symmetry=("Server",))  # its symmetry quotient
TOY_T3 = _cfg(max_term=3)             # term-widened universe
TOY_M3 = _cfg(max_msgs=3)             # channel-widened universe

JOBS = ([(f"toy-{i}", TOY) for i in range(8)]
        + [(f"sym-{i}", TOY_SYM) for i in range(4)]
        + [(f"t3-{i}", TOY_T3) for i in range(2)]
        + [(f"m3-{i}", TOY_M3) for i in range(2)])


def fiducial() -> dict:
    """Synthetic step + copy, jitted and timed warm (chip/CPU weather)."""
    x = jnp.arange(1 << 24, dtype=jnp.uint32)          # 64 MB

    @jax.jit
    def step(v):
        return (v * jnp.uint32(2654435761) ^ (v >> 7)).sum()

    step(x).block_until_ready()                        # compile
    t0 = time.monotonic()
    step(x).block_until_ready()
    step_ms = (time.monotonic() - t0) * 1e3
    t0 = time.monotonic()
    jnp.array(x, copy=True).block_until_ready()
    copy_ms = (time.monotonic() - t0) * 1e3
    return {"synthetic_step_ms": round(step_ms, 2),
            "copy_64mb_ms": round(copy_ms, 2)}


def run_solo() -> tuple:
    """The solo arm: 16 sequential Engine runs, one compile each (a new
    closure per Engine — exactly what 16 separate submissions pay)."""
    t0 = time.monotonic()
    results = {jid: Engine(cfg).check() for jid, cfg in JOBS}
    return time.monotonic() - t0, results


def run_batch() -> tuple:
    t0 = time.monotonic()
    out = BatchExecutor(chunk=CHUNK).run(JOBS)
    wall = time.monotonic() - t0
    assert all(oc.status == "completed" for oc in out.values()), \
        {j: oc.status for j, oc in out.items()}
    return wall, {jid: oc.result for jid, oc in out.items()}


def assert_parity(solo: dict, batch: dict) -> int:
    total = 0
    for jid, _cfg_ in JOBS:
        a, b = solo[jid], batch[jid]
        for field in ("n_states", "diameter", "n_transitions"):
            assert getattr(a, field) == getattr(b, field), \
                (jid, field, getattr(a, field), getattr(b, field))
        assert list(a.levels) == list(b.levels), jid
        assert dict(a.coverage) == dict(b.coverage), jid
        assert a.complete and b.complete and a.violation is None \
            and b.violation is None, jid
        total += a.n_states
    return total


def main():
    reps = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    n_bins = len({bin_key(cfg) for _jid, cfg in JOBS})
    walls: dict = {"solo": [], "batch": []}
    n_total = None
    with open(OUT, "a") as out:
        for rep in range(reps):
            for arm in ("solo", "batch"):   # interleaved: drift is shared
                fid = fiducial()
                wall, results = run_solo() if arm == "solo" \
                    else run_batch()
                walls[arm].append(wall)
                if arm == "solo":
                    solo_results = results
                else:
                    n_total = assert_parity(solo_results, results)
                line = {"rep": rep, "arm": arm, "wall_s": round(wall, 2),
                        "jobs": len(JOBS), "bins": n_bins,
                        "platform": jax.default_backend(), **fid}
                print(json.dumps(line))
                out.write(json.dumps(line) + "\n")
                out.flush()
        med = {a: statistics.median(w) for a, w in walls.items()}
        rate = {a: round(n_total / med[a], 1) for a in med}
        summary = {
            "summary": "serve_ab",
            "jobs": len(JOBS), "bins": n_bins, "chunk": CHUNK,
            "aggregate_states": n_total,
            "reps": reps,
            "parity": "byte-identical on every rep",
            "median_wall_s": {a: round(m, 2) for a, m in med.items()},
            "aggregate_states_per_sec": rate,
            "batch_over_solo_rate": round(rate["batch"] / rate["solo"], 4),
            "pass_ge_0.8": rate["batch"] / rate["solo"] >= 0.8,
        }
        print(json.dumps(summary))
        out.write(json.dumps(summary) + "\n")


if __name__ == "__main__":
    main()
