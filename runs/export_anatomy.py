"""VERDICT r4 weak #4 / next #7: where does the liveness graph export
spend its time?  Reproduces ddd_graph's re-expansion loop with per-phase
timers on the 3-server election SYMMETRY quotient (23,902 orbits)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
if "--tpu" not in sys.argv:
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.ddd_engine import DDDEngine
from raft_tla_tpu.models import spec as S
from raft_tla_tpu.ops import kernels
from raft_tla_tpu.utils import keyset

config = CheckConfig(
    bounds=Bounds(n_servers=3, n_values=1, max_term=2, max_log=0,
                  max_msgs=1),
    spec="election", invariants=(), symmetry=("Server",), chunk=1024)

t0 = time.monotonic()
eng = DDDEngine(config)
eng.check(retain_store=True)
host, constore, keystore, n = eng.retained
t_bfs = time.monotonic() - t0
print(f"BFS: {n} orbits in {t_bfs:.2f}s ({n / t_bfs:,.0f}/s)")

bounds, lay, schema, table = config.bounds, eng.lay, eng.schema, eng.table
A, B = eng.A, config.chunk
kw = keystore.read(0, n).view(np.uint32)
keys = keyset.pack_keys(kw[:, 1], kw[:, 0])
order = np.argsort(keys)
sorted_keys = keys[order]
expanded = constore.read(0, n)[:, 0].astype(bool)

step = jax.jit(kernels.build_step(bounds, config.spec, (),
                                  config.symmetry, view=config.view))

T = dict(read=0.0, unpack=0.0, dispatch=0.0, harvest=0.0, pack=0.0,
         assemble=0.0)
t_all = time.monotonic()
e_cnt = 0
for c0 in range(0, n, B):
    nb = min(B, n - c0)
    t = time.monotonic(); rows = host.read(c0, nb); T["read"] += time.monotonic() - t
    t = time.monotonic()
    vecs = schema.unpack(rows, np)
    if nb < B:
        vecs = np.concatenate(
            [vecs, np.broadcast_to(vecs[:1], (B - nb, vecs.shape[1]))])
    T["unpack"] += time.monotonic() - t
    t = time.monotonic()
    out = step(jnp.asarray(vecs))
    jax.block_until_ready(out["valid"])
    T["dispatch"] += time.monotonic() - t
    t = time.monotonic()
    valid = np.asarray(out["valid"])[:nb]
    fph = np.asarray(out["fp_hi"])[:nb].reshape(nb, A)
    fpl = np.asarray(out["fp_lo"])[:nb].reshape(nb, A)
    T["harvest"] += time.monotonic() - t
    t = time.monotonic()
    skeys = keyset.pack_keys(fph, fpl)
    T["pack"] += time.monotonic() - t
    t = time.monotonic()
    b_idx, a_idx = np.nonzero(valid)
    u_idx = (c0 + b_idx).astype(np.int64)
    m = expanded[u_idx]
    sk = skeys[b_idx[m], a_idx[m]]
    pos = np.searchsorted(sorted_keys, sk)
    e_cnt += sk.size
    T["assemble"] += time.monotonic() - t
wall = time.monotonic() - t_all
print(f"export loop: {n} orbits, {e_cnt} edges in {wall:.2f}s "
      f"({n / wall:,.0f} orbits/s)")
for k, v in sorted(T.items(), key=lambda kv: -kv[1]):
    print(f"  {k:9} {v:7.2f}s  {100 * v / wall:5.1f}%")
host.close(); constore.close(); keystore.close()

# -- the restructured ddd_graph export, end to end --------------------
import dataclasses as _dc
from raft_tla_tpu.models import liveness
t1 = time.monotonic()
states, edges, enabled, expanded2 = liveness.ddd_graph(config)
t_new = time.monotonic() - t1
print(f"ddd_graph (segmented slim export, incl. its own BFS): "
      f"{len(states)} orbits, {edges.n_edges} edges in {t_new:.2f}s")
states.close()
