"""VERDICT r3 item #5 (the measurement-protocol gate): decompose
ddd_engine._filter_insert on the real chip and name the cause of the
round-3 synthetic-vs-real ~1000x microbenchmark anomaly before any
round-4 kernel number is trusted.

Timed variants, each on BOTH real step outputs and synthetic random
keys (the two input families whose disagreement is the anomaly):

- ``full_nd``       — the r3 ablation's measurement: standalone jit, NO
                      donation (XLA copies the 2x256 MB table per call).
- ``full_chain``    — donated jit called K times with the table threaded
                      through (the dispatch-level in-place pattern).
- ``full_loop``     — a jitted fori_loop with the table as loop carry:
                      the EXACT in-engine shape (_build_segment inlines
                      _filter_insert into a while_loop body).
- ``sort_only``     — the lexsort + first-of-key pass.
- ``probe_only``    — the bucket gather + seen reduction.
- ``insert_only``   — the two at[].set scatters (donated, loop carry).
- ``copy_only``     — tbl + 0 (the non-donated copy's floor).

Per-rep times come from diffing consecutive block_until_ready stamps
over REPS reps (the r3 harness's average-of-asynchronous-dispatches is
kept for comparison as *_async).

Writes JSON lines to stdout; run on the real chip (no args).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.ddd_engine import _filter_insert
from raft_tla_tpu.device_engine import _EMPTY, BUCKET
from raft_tla_tpu.models import spec as S
from raft_tla_tpu.ops import kernels

from filter_ablation import CFG, TABLE, frontier_rows

I32 = jnp.int32
U32 = jnp.uint32
REPS = 20
CHAIN = 10


def timed_sync(fn, *args, reps=REPS):
    """Warm once, then time each rep to completion (no async pileup)."""
    out = fn(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def timed_async(fn, *args, reps=REPS):
    """The r3 harness: dispatch reps asynchronously, block once."""
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def sort_only(key_hi, key_lo, active):
    BA = key_hi.shape[0]
    skh = jnp.where(active, key_hi, _EMPTY)
    skl = jnp.where(active, key_lo, _EMPTY)
    perm = jnp.lexsort((skl, skh))
    ph, pl_, pa = key_hi[perm], key_lo[perm], active[perm]
    same_as_prev = jnp.concatenate([
        jnp.zeros((1,), bool),
        (ph[1:] == ph[:-1]) & (pl_[1:] == pl_[:-1]) & pa[1:] & pa[:-1]])
    first_of_key = jnp.zeros((BA,), bool).at[perm].set(~same_as_prev)
    return active & first_of_key


def probe_only(tbl_hi, tbl_lo, key_hi, key_lo, active):
    TB = tbl_hi.shape[0]
    bmask = jnp.uint32(TB - 1)
    bidx = (key_lo & bmask).astype(I32)
    row_hi, row_lo = tbl_hi[bidx], tbl_lo[bidx]
    seen = jnp.any((row_hi == key_hi[:, None])
                   & (row_lo == key_lo[:, None]), axis=1)
    return active & ~seen


def insert_only(tbl_hi, tbl_lo, key_hi, key_lo, stream):
    TB, Sb = tbl_hi.shape
    bmask = jnp.uint32(TB - 1)
    bidx = (key_lo & bmask).astype(I32)
    row_hi, row_lo = tbl_hi[bidx], tbl_lo[bidx]
    slot_empty = (row_hi == _EMPTY) & (row_lo == _EMPTY)
    has_empty = jnp.any(slot_empty, axis=1)
    evict = (key_hi % jnp.uint32(Sb)).astype(I32)
    wslot = jnp.where(has_empty, jnp.argmax(slot_empty, axis=1), evict)
    wb = jnp.where(stream, bidx, TB)
    tbl_hi = tbl_hi.at[wb, wslot].set(key_hi, mode="drop")
    tbl_lo = tbl_lo.at[wb, wslot].set(key_lo, mode="drop")
    return tbl_hi, tbl_lo


def main() -> None:
    backend = jax.devices()[0].platform
    A = len(S.action_table(CFG.bounds, CFG.spec))
    B = CFG.chunk
    N = B * A
    step = jax.jit(kernels.build_step(CFG.bounds, CFG.spec,
                                      tuple(CFG.invariants),
                                      CFG.symmetry))
    vecs = jnp.asarray(frontier_rows(B))
    out = jax.block_until_ready(step(vecs))

    TB = TABLE // BUCKET
    fresh = lambda: (jnp.full((TB, BUCKET), _EMPTY, U32),
                     jnp.full((TB, BUCKET), _EMPTY, U32))

    inputs = {}
    kh = out["fp_hi"].reshape(N)
    kl = out["fp_lo"].reshape(N)
    act = out["valid"].reshape(N)
    inputs["real"] = (kh, kl, act)
    rng = np.random.default_rng(7)
    inputs["synth"] = (
        jnp.asarray(rng.integers(0, 1 << 32, N, dtype=np.uint64)
                    .astype(np.uint32)),
        jnp.asarray(rng.integers(0, 1 << 32, N, dtype=np.uint64)
                    .astype(np.uint32)),
        jnp.ones((N,), bool))

    stats = {}
    for nm, (h, l, a) in inputs.items():
        hh = np.asarray(h).astype(np.uint64)
        ll = np.asarray(l).astype(np.uint64)
        keys = (hh << np.uint64(32)) | ll
        aa = np.asarray(a)
        stats[nm] = {
            "n": int(N),
            "active": int(aa.sum()),
            "distinct_active_keys": int(np.unique(keys[aa]).size),
            "distinct_inactive_keys": int(np.unique(keys[~aa]).size)
            if (~aa).any() else 0,
        }
    print(json.dumps({"backend": backend, "chunk": B, "lanes": A,
                      "table_slots": TABLE, "key_stats": stats}),
          flush=True)

    filt_nd = jax.jit(_filter_insert)
    filt_d = jax.jit(_filter_insert, donate_argnums=(0, 1))
    jsort = jax.jit(sort_only)
    jprobe = jax.jit(probe_only)
    jinsert = jax.jit(insert_only, donate_argnums=(0, 1))
    jcopy = jax.jit(lambda th, tl: (th + jnp.uint32(0),
                                    tl + jnp.uint32(0)))

    def chain_d(th, tl, h, l, a):
        # donated chained dispatches; fresh tables consumed
        for _ in range(CHAIN):
            th, tl, strm = filt_d(th, tl, h, l, a)
        return th, tl, strm

    @jax.jit
    def loop_d(th, tl, h, l, a):
        def body(_, c):
            th, tl = c
            th, tl, strm = _filter_insert(th, tl, h, l, a)
            return th, tl
        th, tl = jax.lax.fori_loop(0, CHAIN, body, (th, tl))
        return th, tl

    for nm, (h, l, a) in inputs.items():
        res = {"inputs": nm}

        th, tl = fresh()
        res["full_nd_sync_ms"] = round(
            timed_sync(filt_nd, th, tl, h, l, a) * 1e3, 3)
        res["full_nd_async_ms"] = round(
            timed_async(filt_nd, th, tl, h, l, a) * 1e3, 3)

        # donated chain: cost per call, table threaded through
        th, tl = fresh()
        jax.block_until_ready(chain_d(th, tl, h, l, a))  # warm
        th, tl = fresh()
        t0 = time.perf_counter()
        jax.block_until_ready(chain_d(th, tl, h, l, a))
        res["full_chain_donated_ms"] = round(
            (time.perf_counter() - t0) / CHAIN * 1e3, 3)

        # fori_loop carry: the in-engine shape
        th, tl = fresh()
        jax.block_until_ready(loop_d(th, tl, h, l, a))   # warm+consume
        th, tl = fresh()
        t0 = time.perf_counter()
        jax.block_until_ready(loop_d(th, tl, h, l, a))
        res["full_loop_carry_ms"] = round(
            (time.perf_counter() - t0) / CHAIN * 1e3, 3)

        res["sort_only_ms"] = round(timed_sync(jsort, h, l, a) * 1e3, 3)

        th, tl = fresh()
        res["probe_only_ms"] = round(
            timed_sync(jprobe, th, tl, h, l, a) * 1e3, 3)

        # insert on a realistic stream mask (the full filter's own)
        th, tl = fresh()
        _, _, strm = jax.block_until_ready(filt_nd(th, tl, h, l, a))
        ts = []
        for _ in range(REPS):
            th, tl = fresh()
            jax.block_until_ready((th, tl))
            t0 = time.perf_counter()
            th, tl = jinsert(th, tl, h, l, strm)
            jax.block_until_ready((th, tl))
            ts.append(time.perf_counter() - t0)
        res["insert_only_donated_ms"] = round(
            float(np.median(ts)) * 1e3, 3)
        res["stream_count"] = int(np.asarray(strm).sum())

        th, tl = fresh()
        res["copy_only_ms"] = round(
            timed_sync(jcopy, th, tl) * 1e3, 3)

        print(json.dumps(res), flush=True)


if __name__ == "__main__":
    main()
