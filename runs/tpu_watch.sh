#!/bin/bash
# Probe the TPU tunnel every 5 min; exit 0 the moment it answers.
for i in $(seq 1 120); do
  if timeout 90 python -c "import jax; d=jax.devices(); assert d; print(d)" >/tmp/tpu_probe.out 2>&1; then
    echo "$(date -u) probe $i: TPU AVAILABLE: $(cat /tmp/tpu_probe.out)"
    exit 0
  fi
  echo "$(date -u) probe $i: TPU unavailable"
  sleep 240
done
echo "$(date -u) watcher exhausted 120 probes"
exit 1
