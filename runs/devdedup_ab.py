"""A/B of the device-resident within-level fingerprint dedup
(ops/devdedup.py, RAFT_TLA_DEVDEDUP) — decides the devdedup auto policy.
Protocol per the sig-prune/megakernel/hostdedup/prefetch rounds:
chip-state fiducials via ``bench.py --fiducial`` bracketing the session
(now including the pinned ``d2h_export_rows_per_sec`` harvest probe),
3 interleaved reps per retention, medians, per-rep parity asserts:

- **segment-stream parity**: the off and on arms must report identical
  ``n_states`` at every common-prefix segment (the gate's byte-identity
  contract — the device set only drops rows the host master keyset
  would reject anyway, in the same stream order);
- **export-row accounting**: at every common-prefix segment,
  ``off.export_rows == on.export_rows + on.dev_dedup_hits`` — each row
  the device tier kept off the d2h path is individually accounted for,
  so "saved rows" is an identity, never an estimate.

Statistic: the saved-row fraction (``dev_dedup_hits / off.export_rows``,
the measured within-level duplicate rate of the workload) and the
on/off warm orbits/s ratio, median across reps.  PASS = rows saved at
the measured duplicate rate AND warm rate >= 0.95x off in both
retentions.  On a 1-core CPU container the "d2h" path is a memcpy and
the filter dispatch competes with the harvest loop for the same core,
so the rate half is expected to REFUTE here (the hostdedup and
prefetch rounds measured the same shape honestly) — recorded as such,
with the on-chip re-A/B queued alongside ROADMAP item 2's jobs; the
saved-row accounting identity must hold regardless.

Usage: python runs/devdedup_ab.py [--cpu] [reps]
Artifact: runs/devdedup_ab.out (RESULTS.md "Device dedup A/B").
"""
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

if "--cpu" in sys.argv:
    jax.config.update("jax_platforms", "cpu")

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine

_ints = [int(a) for a in sys.argv[1:] if a.isdigit()]
REPS = _ints[0] if _ints else 3
DEADLINE_S = 45.0                  # per in-engine arm


def _fiducial():
    """bench.py --fiducial in a child (fresh jit caches, pinned gates)."""
    bench = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
    try:
        out = subprocess.run(
            [sys.executable, bench, "--fiducial"], capture_output=True,
            text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS":
                 jax.default_backend()}).stdout
        return json.loads(out.strip().splitlines()[-1])
    except Exception as e:                       # fiducial is evidence,
        return {"fiducial_error": repr(e)}       # not a gate — record


results = {"platform": jax.devices()[0].platform, "reps": REPS,
           "nproc": os.cpu_count() or 1, "inengine": {}}
results["fiducial_start"] = _fiducial()
print("fiducial_start:", json.dumps(results["fiducial_start"]),
      flush=True)

# -- in-engine A/B: flagship-shape DDD probe, off vs hash, both retentions
cfg = CheckConfig(bounds=Bounds(n_servers=3, n_values=2, max_term=2,
                                max_log=1, max_msgs=2, max_dup=1),
                  spec="full",
                  invariants=("NoTwoLeaders", "LogMatching",
                              "CommittedWithinLog", "LeaderCompleteness"),
                  symmetry=("Server",), chunk=4096)
for retention in ("full", "frontier"):
    caps = DDDCapacities(block=1 << 18, table=1 << 22, flush=1 << 22,
                         levels=128, retention=retention)
    per_rep: dict = {"off": [], "on": []}
    results["inengine"][retention] = {"reps": []}
    for rep in range(REPS):
        streams: dict = {}
        rep_rec: dict = {}
        for mode in ("off", "hash"):           # interleaved within the rep
            os.environ["RAFT_TLA_DEVDEDUP"] = mode
            stats: list = []
            t0 = time.monotonic()
            try:
                r = DDDEngine(cfg, caps).check(deadline_s=DEADLINE_S,
                                               on_progress=stats.append)
            finally:
                os.environ.pop("RAFT_TLA_DEVDEDUP", None)
            wall = time.monotonic() - t0
            arm = "off" if mode == "off" else "on"
            streams[arm] = stats
            if len(stats) >= 2:      # warm rate, compile segment excluded
                d_states = stats[-1]["n_states"] - stats[0]["n_states"]
                d_wall = stats[-1]["wall_s"] - stats[0]["wall_s"]
            else:
                d_states, d_wall = r.n_states, wall
            rec = {"wall_s": round(wall, 2), "states": r.n_states,
                   "level": stats[-1]["level"] if stats else 0,
                   "states_per_sec": round(d_states / max(d_wall, 1e-9),
                                           1),
                   "segments": len(stats),
                   "export_rows": stats[-1]["export_rows"]
                   if stats else 0}
            if arm == "on" and stats:
                rec["dev_dedup_hits"] = stats[-1].get("dev_dedup_hits")
            per_rep[arm].append(rec)
            rep_rec[arm] = rec
        # segment-stream parity on the common prefix
        n_common = min(len(streams["off"]), len(streams["on"]))
        assert n_common > 0, "an arm produced no segments"
        for i in range(n_common):
            so, sn = streams["off"][i], streams["on"][i]
            assert so["n_states"] == sn["n_states"], \
                f"segment n_states parity failed ({retention} rep {rep} " \
                f"segment {i}: {so['n_states']} vs {sn['n_states']})"
            # export-row accounting: every dropped row is a counted hit
            assert so["export_rows"] == (sn["export_rows"]
                                         + sn["dev_dedup_hits"]), \
                f"export-row accounting failed ({retention} rep {rep} " \
                f"segment {i}: off {so['export_rows']} != on " \
                f"{sn['export_rows']} + hits {sn['dev_dedup_hits']})"
        last = streams["on"][n_common - 1]
        off_last = streams["off"][n_common - 1]
        saved = (last["dev_dedup_hits"]
                 / max(off_last["export_rows"], 1))
        rep_rec["parity_segments"] = n_common
        rep_rec["saved_row_fraction"] = round(saved, 4)
        results["inengine"][retention]["reps"].append(rep_rec)
        print(f"{retention:8} rep {rep}: off "
              f"{rep_rec['off']['states_per_sec']:>9,.0f}/s "
              f"({rep_rec['off']['export_rows']:,} rows)   on "
              f"{rep_rec['on']['states_per_sec']:>9,.0f}/s "
              f"({rep_rec['on']['export_rows']:,} rows, "
              f"{rep_rec['on']['dev_dedup_hits']:,} hits, "
              f"{saved:.1%} saved @ {n_common} parity segments)",
              flush=True)
    # medians across reps
    med = {}
    for arm in ("off", "on"):
        rates = sorted(r["states_per_sec"] for r in per_rep[arm])
        med[arm] = rates[len(rates) // 2]
    saves = sorted(r["saved_row_fraction"]
                   for r in results["inengine"][retention]["reps"])
    summ = results["inengine"][retention]
    summ["off_warm_rate_median"] = med["off"]
    summ["on_warm_rate_median"] = med["on"]
    summ["on_vs_off_warm_rate"] = round(med["on"] / max(med["off"], 1e-9),
                                        3)
    summ["saved_row_fraction_median"] = saves[len(saves) // 2]

worst_ratio = min(results["inengine"][r]["on_vs_off_warm_rate"]
                  for r in ("full", "frontier"))
any_saved = min(results["inengine"][r]["saved_row_fraction_median"]
                for r in ("full", "frontier"))
results["gate_pass"] = bool(worst_ratio >= 0.95)
print(f"verdict: rows saved full "
      f"{results['inengine']['full']['saved_row_fraction_median']:.1%} / "
      f"frontier "
      f"{results['inengine']['frontier']['saved_row_fraction_median']:.1%}"
      f", on/off warm rate full "
      f"{results['inengine']['full']['on_vs_off_warm_rate']:.3f}x / "
      f"frontier "
      f"{results['inengine']['frontier']['on_vs_off_warm_rate']:.3f}x -> "
      + ("PASS" if results["gate_pass"] else
         "REFUTED on this host (the d2h path is a memcpy and the filter "
         "dispatch time-slices the harvest core; accounting identity "
         "held — on-chip re-A/B queued)"), flush=True)

results["fiducial_end"] = _fiducial()
print("fiducial_end:", json.dumps(results["fiducial_end"]), flush=True)
print(json.dumps(results))
