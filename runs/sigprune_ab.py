"""Sync-timed A/B of signature-refinement orbit-scan pruning
(ops/symmetry.build_orbit_fp ``prune=``) — decides the
_sigprune_enabled auto policy.  The pruned scan probes exact server/
value interchangeability per state (transposition probes gated by a
cheap signature prefilter) and scans one permutation per coset of the
verified stabilizer; its payoff therefore depends entirely on how
symmetric the CHUNK is: a rung only engages when the chunk-max kept
count fits it, i.e. when EVERY state in the chunk has a non-trivial
verified stabilizer.

Two measurements per shape, both with parity asserted bit-for-bit
against the unpruned scan (the r3/r4 protocol: block_until_ready
between reps, median of reps), at |G| = 6 (flagship), 24 (elect4) and
120 (elect5):

- ``mid``: distinct mid-depth rows, the prescan_ab pool — the regime
  the flagship/elect5 campaigns actually spend their wall in, where
  states are dominated by fully-asymmetric role/term/log assignments;
- ``shallow``: the first BFS levels tiled to the chunk — the
  symmetric-rich regime (few elections have happened; most servers are
  exactly interchangeable) where the rungs can engage.

Plus an in-engine DDD A/B (RAFT_TLA_SIGPRUNE=off vs on, engines built
fresh per arm — the gate is read at step-build time) asserting
n_states/diameter/transitions parity and comparing end-to-end wall.

Usage: python runs/sigprune_ab.py [--cpu] [reps] [chunk]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

if "--cpu" in sys.argv:
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.models import interp
from raft_tla_tpu.ops import kernels

_ints = [int(a) for a in sys.argv[1:] if a.isdigit()]
REPS = _ints[0] if _ints else 7
B = _ints[1] if len(_ints) > 1 else 1024

SHAPES = {
    "flagship": (Bounds(n_servers=3, n_values=2, max_term=2, max_log=1,
                        max_msgs=2, max_dup=1),
                 "full", ("NoTwoLeaders", "LogMatching",
                          "CommittedWithinLog", "LeaderCompleteness")),
    "elect4": (Bounds(n_servers=4, n_values=2, max_term=2, max_log=0,
                      max_msgs=2, max_dup=1),
               "election", ("NoTwoLeaders", "CommittedWithinLog")),
    "elect5": (Bounds(n_servers=5, n_values=2, max_term=2, max_log=0,
                      max_msgs=2, max_dup=1),
               "election", ("NoTwoLeaders", "CommittedWithinLog")),
}


def _pools(bounds, spec):
    """(mid, shallow) row pools, each exactly B rows."""
    init = interp.init_state(bounds)
    frontier, seen, mid = [init], {init}, []
    shallow, depth = [init], 0
    while len(mid) < B:
        if not frontier:
            raise SystemExit(f"space exhausted below {B} distinct rows")
        nxt = []
        for s in frontier:
            if not interp.constraint_ok(s, bounds):
                continue
            for _i, t in interp.successors(s, bounds, spec=spec):
                if t not in seen:
                    seen.add(t)
                    nxt.append(t)
        frontier = nxt
        depth += 1
        if depth <= 2:
            shallow += [s for s in frontier
                        if interp.constraint_ok(s, bounds)]
        mid = [s for s in frontier if interp.constraint_ok(s, bounds)]
    mid_rows = np.stack([interp.to_vec(s, bounds) for s in mid[:B]])
    srows = np.stack([interp.to_vec(s, bounds) for s in shallow])
    shallow_rows = np.tile(srows, (-(-B // len(srows)), 1))[:B]
    return mid_rows, shallow_rows


def _time_step(bounds, spec, invs, vecs):
    """(ms_off, ms_pruned), parity-asserted."""
    out, ref_fp = {}, None
    for name, gate in (("off", lambda *_: False),
                       ("pruned", lambda *_: True)):
        saved = kernels._sigprune_enabled
        kernels._sigprune_enabled = gate    # measure the comparison the
        try:                                # gate encodes — bypass it
            fn = jax.jit(kernels.build_step(bounds, spec, invs,
                                            ("Server",)))
            r = fn(vecs)
            jax.block_until_ready(r)
        finally:
            kernels._sigprune_enabled = saved
        fp = (np.asarray(r["fp_hi"]), np.asarray(r["fp_lo"]))
        if ref_fp is None:
            ref_fp = fp
        else:
            assert np.array_equal(fp[0], ref_fp[0])
            assert np.array_equal(fp[1], ref_fp[1])
        times = []
        for _ in range(REPS):
            t0 = time.monotonic()
            jax.block_until_ready(fn(vecs))
            times.append(time.monotonic() - t0)
        out[name] = sorted(times)[len(times) // 2]
    return out["off"], out["pruned"]


results = {"platform": jax.devices()[0].platform, "chunk": B,
           "reps": REPS, "step": {}, "inengine": {}}
for shape, (bounds, spec, invs) in SHAPES.items():
    mid, shallow = _pools(bounds, spec)
    results["step"][shape] = {}
    for pool, rows in (("mid", mid), ("shallow", shallow)):
        ms_off, ms_pr = _time_step(bounds, spec, invs, jnp.asarray(rows))
        results["step"][shape][pool] = {
            "ms_off": round(ms_off * 1e3, 2),
            "ms_pruned": round(ms_pr * 1e3, 2),
            "speedup_from_prune": round(ms_off / ms_pr, 3)}
        print(f"{shape:9} {pool:8} off {ms_off * 1e3:8.2f} ms/chunk  "
              f"pruned {ms_pr * 1e3:8.2f} ms/chunk  "
              f"({ms_off / ms_pr:5.2f}x)", flush=True)

# in-engine: fresh DDD engines per arm (the gate is read at build time).
# |G|=24 election space, ONE value (values are inert at max_log=0, so
# this halves the wall without changing the symmetry structure) and ONE
# message slot per type (the m2 variant's single arm blew a 60-min solo
# window on the 1-core host) — small enough to run EXHAUSTIVELY twice
# on a single CPU core, deep enough that mid-depth chunks dominate the
# wall like a real campaign.
from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine

cfg = CheckConfig(bounds=Bounds(n_servers=4, n_values=1, max_term=2,
                                max_log=0, max_msgs=1, max_dup=1),
                  spec="election",
                  invariants=("NoTwoLeaders",), symmetry=("Server",),
                  chunk=B)
caps = DDDCapacities(block=1 << 14, table=1 << 16, flush=1 << 16,
                     levels=64)
parity = {}
for mode in ("off", "on"):
    os.environ["RAFT_TLA_SIGPRUNE"] = mode
    t0 = time.monotonic()
    r = DDDEngine(cfg, caps).check()
    wall = time.monotonic() - t0
    parity[mode] = (r.n_states, r.diameter, r.n_transitions)
    results["inengine"][mode] = {
        "wall_s": round(wall, 2), "n_states": r.n_states,
        "diameter": r.diameter, "n_transitions": r.n_transitions}
    print(f"inengine  {mode:3}  {wall:7.2f} s  {r.n_states} states "
          f"diameter {r.diameter}", flush=True)
os.environ.pop("RAFT_TLA_SIGPRUNE", None)
assert parity["on"] == parity["off"], parity
results["inengine"]["speedup_from_prune"] = round(
    results["inengine"]["off"]["wall_s"]
    / max(results["inengine"]["on"]["wall_s"], 1e-9), 3)

print(json.dumps(results))
