"""Round-2 flagship re-verification on the DDD engine.

Same space as runs/flagship_r2.py (reference raft.cfg universe: 3s/2v
full `Next`, t2/l1/m2, SYMMETRY Server; round-1 result 94,396,461
orbits, diameter 57, 4 invariants hold, ~6.4 h).  The paged-engine rerun
measured ~8k orbits/s with its full-capacity 2^28-slot table (the table
engines pay HBM traffic per dedup probe that the small-table bench probe
masked); the DDD engine keeps exact dedup in host RAM and sustained
18-29k orbits/s on elect5's 120-permutation workload — this universe's
orbit pass is 20x lighter (P = 6).

Usage: python runs/flagship_r2_ddd.py [resume]
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine

RUNS = os.path.dirname(os.path.abspath(__file__))
CKPT = os.path.join(RUNS, "flagship_r2_ddd.ckpt")
STATS = os.path.join(RUNS, "flagship_r2_ddd.stats")

CFG = CheckConfig(
    bounds=Bounds(n_servers=3, n_values=2, max_term=2, max_log=1,
                  max_msgs=2, max_dup=1),
    spec="full",
    invariants=("NoTwoLeaders", "LogMatching", "CommittedWithinLog",
                "LeaderCompleteness"),
    symmetry=("Server",), chunk=4096)

CAPS = DDDCapacities(block=1 << 20, table=1 << 26, seg_rows=1 << 19,
                     flush=1 << 23, levels=1 << 10)


def main():
    resume = CKPT if (len(sys.argv) > 1 and sys.argv[1] == "resume") \
        else None
    sf = open(STATS, "a", buffering=1)
    eng = DDDEngine(CFG, CAPS)
    r = eng.check(on_progress=lambda s: sf.write(json.dumps(s) + "\n"),
                  checkpoint=CKPT, checkpoint_every_s=600.0,
                  resume=resume)
    print(json.dumps({
        "n_states": r.n_states, "diameter": r.diameter,
        "n_transitions": r.n_transitions, "complete": r.complete,
        "violation": r.violation.invariant if r.violation else None,
        "levels": r.levels, "wall_s": round(r.wall_s, 1),
    }))


if __name__ == "__main__":
    main()
