"""Time-boxed DDD-engine probes on the real chip.

Usage: python runs/probe_ddd.py <workload> <deadline_s> <chunk> [route_rows]
  workload: ns  = north-star-shaped symmetric full-Next 3s/2v (bench probe)
            e5  = elect5-shaped symmetric 5s election t2/m2
            c4  = config #4: symmetric full-Next 5s/2v t2/l1/m2
Prints one JSON line of warm rates (same split as bench.run_northstar).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine

WORKLOADS = {
    "ns": dict(bounds=Bounds(n_servers=3, n_values=2, max_term=2,
                             max_log=1, max_msgs=2, max_dup=1),
               spec="full",
               invariants=("NoTwoLeaders", "LogMatching",
                           "CommittedWithinLog", "LeaderCompleteness")),
    "e5": dict(bounds=Bounds(n_servers=5, n_values=2, max_term=2,
                             max_log=0, max_msgs=2, max_dup=1),
               spec="election",
               invariants=("NoTwoLeaders", "CommittedWithinLog")),
    "c4": dict(bounds=Bounds(n_servers=5, n_values=2, max_term=2,
                             max_log=1, max_msgs=2, max_dup=1),
               spec="full",
               invariants=("NoTwoLeaders", "LogMatching",
                           "CommittedWithinLog", "LeaderCompleteness")),
}


def main():
    wl, deadline, chunk = (sys.argv[1], float(sys.argv[2]),
                           int(sys.argv[3]))
    route = int(sys.argv[4]) if len(sys.argv) > 4 else 0
    cfg = CheckConfig(symmetry=("Server",), chunk=chunk, **WORKLOADS[wl])
    eng = DDDEngine(cfg, DDDCapacities(block=1 << 20, table=1 << 26,
                                       flush=1 << 23, levels=1 << 12,
                                       route_rows=route))
    stats: list = []
    r = eng.check(deadline_s=deadline, on_progress=stats.append)
    if len(stats) >= 2:
        d_orbits = stats[-1]["n_states"] - stats[0]["n_states"]
        d_wall = stats[-1]["wall_s"] - stats[0]["wall_s"]
    else:
        d_orbits, d_wall = r.n_states, r.wall_s
    print(json.dumps({
        "workload": wl, "chunk": chunk, "route_rows": route,
        "orbits": r.n_states,
        "level": stats[-1]["level"] if stats else 0,
        "orbits_per_sec": round(d_orbits / max(d_wall, 1e-9), 1),
        "transitions": r.n_transitions,
        "violation": r.violation is not None,
        "complete": r.complete, "wall_s": round(r.wall_s, 2),
    }))


if __name__ == "__main__":
    main()
