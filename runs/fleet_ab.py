"""Sharded-vs-solo walker-fleet A/B (fleet/engine.FleetSimulator) —
the deciding measurement for the ISSUE 11 tentpole.

Five arms, one pinned simulation config (flagship bounds, spec full,
1024 global walkers, depth 100, 64 steps/dispatch, seed 0):

- ``solo-legacy``: simulate.Simulator with the pre-PR per-dispatch host
  sync storm (one ``bool()``/``int()`` device round-trip per scalar);
- ``solo-fused``: same engine, single fused ``device_get`` per dispatch
  (satellite 1 — this delta isolates the sync-storm cost);
- ``fleet-1 / fleet-2 / fleet-4``: the shard_mapped fleet over 1/2/4
  virtual CPU devices (XLA host-platform device count, set before jax
  import), same global walker count split over the mesh.

Protocol (r3/r4): warm every arm first (compile excluded), then REPS
interleaved rounds (arm order rotates per round so chip weather hits
all arms equally), median wall per arm; chip-state fiducials via
``bench.py --fiducial`` bracket the session.  Parity asserted:

- the three fleet arms must agree BIT-FOR-BIT on (n_behaviors,
  n_states, max_depth_seen, coverage) — the device-count-invariance
  contract;
- solo fused vs legacy must agree exactly (same walks, different
  fetch);
- solo vs fleet agree on states (walkers x depth completes either way)
  but not behaviors (different PRNG stream layouts — documented, not
  asserted equal).

Verdict gate: fleet-2 >= 1.6x fleet-1 sustained states/s.  On a
single-core container the XLA CPU mesh arms share one core, so an
honest refutation here is the expected outcome (same protocol as the
megakernel CPU refutation); the gate is for real multi-device parts.

Usage: python runs/fleet_ab.py [reps] [behaviors]
Artifact: appends one JSON line to runs/fleet_ab.out
(RESULTS.md "Fleet scaling A/B").
"""
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Virtual mesh must exist before any jax import touches a backend.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if "host_platform_device_count" not in f]
os.environ["XLA_FLAGS"] = " ".join(
    _flags + ["--xla_force_host_platform_device_count=4"])

import jax

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.fleet import FleetSimulator
from raft_tla_tpu.parallel.shard_engine import make_mesh
from raft_tla_tpu.simulate import Simulator

_ints = [int(a) for a in sys.argv[1:] if a.isdigit()]
REPS = _ints[0] if _ints else 3
N_BEH = _ints[1] if len(_ints) > 1 else 4096

CFG = CheckConfig(
    bounds=Bounds(n_servers=3, n_values=2, max_term=2, max_log=1,
                  max_msgs=2, max_dup=1),
    spec="full", invariants=("NoTwoLeaders", "LogMatching"))
WALKERS, DEPTH, STEPS, SEED = 1024, 100, 64, 0


def _fiducial():
    bench = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
    try:
        out = subprocess.run(
            [sys.executable, bench, "--fiducial"], capture_output=True,
            text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"}).stdout
        return json.loads(out.strip().splitlines()[-1])
    except Exception as e:                       # evidence, not a gate
        return {"fiducial_error": repr(e)}


def _key(res):
    """The bit-reproducibility fingerprint of one run."""
    return (res.n_behaviors, res.n_states, res.max_depth_seen)


arms = {
    "solo-legacy": Simulator(CFG, walkers=WALKERS, depth=DEPTH,
                             steps_per_dispatch=STEPS, seed=SEED,
                             fetch="legacy"),
    "solo-fused": Simulator(CFG, walkers=WALKERS, depth=DEPTH,
                            steps_per_dispatch=STEPS, seed=SEED),
}
for nd in (1, 2, 4):
    arms[f"fleet-{nd}"] = FleetSimulator(
        CFG, mesh=make_mesh(nd), walkers=WALKERS, depth=DEPTH,
        steps_per_dispatch=STEPS, seed=SEED)

results = {"platform": jax.devices()[0].platform,
           "n_host_devices": len(jax.devices()),
           "reps": REPS, "behaviors": N_BEH, "walkers": WALKERS,
           "depth": DEPTH, "steps_per_dispatch": STEPS, "seed": SEED,
           "arms": {}}
results["fiducial_start"] = _fiducial()
print("fiducial_start:", json.dumps(results["fiducial_start"]),
      flush=True)

keys, walls = {}, {name: [] for name in arms}
for name, sim in arms.items():                    # warm: compile + walks
    keys[name] = _key(sim.run(N_BEH))
    print(f"warm {name:12} -> beh/states/depth {keys[name]}", flush=True)

order = list(arms)
for rep in range(REPS):
    for name in order[rep % len(order):] + order[:rep % len(order)]:
        t0 = time.monotonic()
        res = arms[name].run(N_BEH)
        walls[name].append(time.monotonic() - t0)
        assert _key(res) == keys[name], \
            f"{name}: rep {rep} diverged from warm run"

for name in arms:
    ws = sorted(walls[name])
    wall = ws[len(ws) // 2]
    nb, ns, md = keys[name]
    results["arms"][name] = {
        "wall_s_median": round(wall, 3), "wall_s_all": [
            round(w, 3) for w in walls[name]],
        "n_behaviors": nb, "n_states": ns, "max_depth": md,
        "states_per_sec": round(ns / max(wall, 1e-9), 1)}
    print(f"{name:12} median {wall:7.3f} s  {ns} states  "
          f"({ns / max(wall, 1e-9):,.0f} states/s)", flush=True)

# -- parity gates ----------------------------------------------------------
assert keys["fleet-1"] == keys["fleet-2"] == keys["fleet-4"], \
    "device-count invariance violated: fleet arms disagree"
assert keys["solo-legacy"] == keys["solo-fused"], \
    "fetch-path parity violated: fused and legacy solo runs disagree"
results["fleet_bit_identical_1_2_4"] = True
results["solo_fetch_parity"] = True

r = results["arms"]
results["fleet2_vs_fleet1"] = round(
    r["fleet-2"]["states_per_sec"] / r["fleet-1"]["states_per_sec"], 3)
results["fleet4_vs_fleet1"] = round(
    r["fleet-4"]["states_per_sec"] / r["fleet-1"]["states_per_sec"], 3)
results["fused_vs_legacy"] = round(
    r["solo-fused"]["states_per_sec"]
    / r["solo-legacy"]["states_per_sec"], 3)
results["pass_ge_1.6x_at_2dev"] = results["fleet2_vs_fleet1"] >= 1.6
print(f"scaling: fleet-2 {results['fleet2_vs_fleet1']}x, fleet-4 "
      f"{results['fleet4_vs_fleet1']}x vs fleet-1; fused fetch "
      f"{results['fused_vs_legacy']}x vs legacy; 2-device >=1.6x gate: "
      f"{'PASS' if results['pass_ge_1.6x_at_2dev'] else 'REFUTED'}",
      flush=True)

results["fiducial_end"] = _fiducial()
print("fiducial_end:", json.dumps(results["fiducial_end"]), flush=True)
line = json.dumps(results)
with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fleet_ab.out"), "a") as fh:
    fh.write(line + "\n")
print(line)
