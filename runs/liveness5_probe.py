"""Size probe for config #5 at 5 servers (election t2/m1, SYMMETRY
Server): a deadline-boxed DDD BFS printing per-level growth, to decide
whether the exact fair-lasso checker (practical to a few 1e7 states —
liveness.py docstring) can take the full quotient graph, before
burning hours on a blind export."""
import json, os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
jax.config.update("jax_platforms", "cpu")
from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine

CFG = CheckConfig(
    bounds=Bounds(n_servers=5, n_values=2, max_term=2, max_log=0,
                  max_msgs=1, max_dup=1),
    spec="election", invariants=(), symmetry=("Server",), chunk=1024)

deadline = float(sys.argv[1]) if len(sys.argv) > 1 else 1200.0
eng = DDDEngine(CFG, DDDCapacities(block=1 << 16, table=1 << 20,
                                   seg_rows=1 << 17, flush=1 << 18,
                                   levels=256, retention="frontier"))
r = eng.check(deadline_s=deadline,
              on_progress=lambda s: print(json.dumps(
                  {k: s[k] for k in ("wall_s", "n_states", "level")}),
                  flush=True))
print(json.dumps({"final": r.n_states, "levels": r.levels,
                  "complete": r.complete, "wall_s": round(r.wall_s, 1)}))
