#!/bin/bash
# Retry TPU availability; when it returns, launch the elect5 campaign
# (frontier mode).  Refuses to launch near round end (the driver needs
# the chip for bench, and a campaign must stop with recovery margin),
# and kills a launched campaign at the stop deadline.
LAUNCH_CUTOFF=$(date -u -d "2026-08-01 22:00" +%s)
STOP_AT=$(date -u -d "2026-08-01 22:40" +%s)
cd /root/repo/runs
for i in $(seq 1 200); do
  now=$(date -u +%s)
  if [ "$now" -ge "$LAUNCH_CUTOFF" ]; then
    echo "$(date -u) past launch cutoff; watcher exiting" >> wait_and_resume.log
    exit 0
  fi
  if pgrep -f "elect5_ddd.py resume" > /dev/null; then break; fi
  if timeout 240 python -c "import jax; jax.devices()" > /dev/null 2>&1; then
    echo "$(date -u) TPU back after $i probes; launching campaign" >> wait_and_resume.log
    nohup python elect5_ddd.py resume > elect5ddd_r4.out 2>&1 &
    break
  fi
  echo "$(date -u) probe $i: TPU unavailable" >> wait_and_resume.log
  sleep 120
done
# stop-guard: kill the campaign at STOP_AT so bench gets the chip
while pgrep -f "elect5_ddd.py resume" > /dev/null; do
  now=$(date -u +%s)
  if [ "$now" -ge "$STOP_AT" ]; then
    echo "$(date -u) stop deadline: killing campaign" >> wait_and_resume.log
    pkill -9 -f "elect5_ddd.py resume"
    exit 0
  fi
  sleep 60
done
