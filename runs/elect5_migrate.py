"""Standalone full->frontier migration of the elect5 campaign
checkpoint (round 5): the migration is pure host-side file slicing
(load_frontier_snapshot), so it can run while the TPU tunnel is dead —
a returning chip then resumes straight into the first dispatch instead
of spending its window on a 63 GB rewrite.  Idempotent: if the
checkpoint is already frontier-format this is a no-op open+verify."""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.ddd_engine import _DigestCaps, load_frontier_snapshot
from raft_tla_tpu.models import interp
from raft_tla_tpu.ops import bitpack, symmetry as sym_mod
from raft_tla_tpu.utils import ckpt

RUNS = os.path.dirname(os.path.abspath(__file__))
CKPT = os.path.join(RUNS, "elect5ddd.ckpt")

CFG = CheckConfig(
    bounds=Bounds(n_servers=5, n_values=2, max_term=2, max_log=0,
                  max_msgs=2, max_dup=1),
    spec="election",
    invariants=("NoTwoLeaders", "CommittedWithinLog"),
    symmetry=("Server",), chunk=4096)          # == runs/elect5_ddd.py

init_py = interp.init_state(CFG.bounds)
init_vec = interp.to_vec(init_py, CFG.bounds)
hi0, lo0 = sym_mod.init_fingerprint(CFG, init_py, init_vec)
digest = ckpt.config_digest(
    CFG, _DigestCaps(block=1 << 20, levels=1 << 12), (hi0, lo0))

schema = bitpack.BitSchema(CFG.bounds)
t0 = time.monotonic()
(rows_ls, con_ls, keystore, n_states, n_trans, cov, level_ends,
 blocks_done) = load_frontier_snapshot(CKPT, schema.P, digest)
wall = time.monotonic() - t0
print(json.dumps({
    "n_states": n_states, "n_trans": n_trans,
    "levels": len(level_ends), "blocks_done": blocks_done,
    "cur_span": [rows_ls.cur.base, len(rows_ls.cur)],
    "nxt_span": [rows_ls.nxt.base, len(rows_ls)],
    "wall_s": round(wall, 1)}))
rows_ls.close()
con_ls.close()
keystore.close()
