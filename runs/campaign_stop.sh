#!/bin/bash
# Graceful DDD campaign stop.
# Contract (implemented in ddd_engine._install_sigint):
#   SIGINT once -> the engine stops at the NEXT SEGMENT BOUNDARY: pending
#   candidates are flushed, a snapshot is saved when the run has a
#   --checkpoint path, and the engine returns a normal complete=False
#   result — the campaign wrapper then prints its endpoint JSON
#   (runs/elect5ddd_r5b.out is the r5 artifact of this shape).
#   SIGINT twice -> raw abort (KeyboardInterrupt), for a wedged dispatch.
# The r4/r5 operational traps this encodes:
#   - never SIGKILL first (r4's kill during a wedged dispatch lost the worker
#     for >1h);
#   - after exit, the TPU worker claim needs ~10 min to release before any
#     other process may touch the chip (8d92f00: 2.5 min relaunch wedged,
#     10 min pause ran first try).
# Usage: campaign_stop.sh [ENDPOINT_OUT] [STATS_FILE] [EVENTS_FILE]
set -u
OUT=${1:-/root/repo/runs/elect5ddd_r5b.out}
STATS=${2:-/root/repo/runs/elect5ddd.stats}
EVENTS=${3:-/root/repo/runs/elect5ddd.events}
# match the python invocation itself, not wrappers/editors whose argv
# happens to mention the script (an r5 near-miss: pgrep -f matched the
# tail -f watching the log)
MAPFILE=()
while IFS= read -r line; do MAPFILE+=("$line"); done \
    < <(pgrep -f "python[0-9.]* .*runs/elect5_ddd\.py")
if [ "${#MAPFILE[@]}" -eq 0 ]; then echo "no campaign process"; exit 1; fi
if [ "${#MAPFILE[@]}" -gt 1 ]; then
    echo "ambiguous: ${#MAPFILE[@]} matching processes (${MAPFILE[*]}) —"
    echo "refusing to signal; pick the PID and kill -INT it yourself"
    exit 3
fi
PID=${MAPFILE[0]}
# mark WHY the run is about to stop in the event log BEFORE signaling:
# a run_end that follows a stop_requested is a clean operator stop, one
# without it is a crash — the attribution the r4 postmortem lacked.
# Best-effort: a missing/readonly log must never block the stop itself.
PYTHONPATH=/root/repo python3 -m raft_tla_tpu.obs emit "$EVENTS" \
    stop_requested --reason clean-stop --source campaign_stop.sh \
    --pid "$PID" 2>/dev/null || true
echo "SIGINT -> $PID at $(date -u +%H:%M:%S)"
kill -INT "$PID"
for i in $(seq 1 180); do
    kill -0 "$PID" 2>/dev/null || break
    sleep 10
done
if kill -0 "$PID" 2>/dev/null; then
    echo "still alive after 30 min; NOT escalating (wedge risk) — investigate"
    echo "(a second 'kill -INT $PID' aborts raw WITHOUT the boundary flush)"
    exit 2
fi
echo "campaign exited at $(date -u +%H:%M:%S); endpoint tail:"
tail -3 "$OUT"
tail -1 "$STATS"
echo "worker-claim release pause: wait 10 min before the next chip job"
