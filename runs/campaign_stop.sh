#!/bin/bash
# Graceful elect5 campaign stop (round-5 endgame procedure).
# SIGINT once -> the engine checkpoints at the next segment boundary and
# exits with the endpoint JSON on stdout (runs/elect5ddd_r5b.out).
# The r4/r5 operational traps this encodes:
#   - never SIGKILL first (r4's kill during a wedged dispatch lost the worker
#     for >1h);
#   - after exit, the TPU worker claim needs ~10 min to release before any
#     other process may touch the chip (8d92f00: 2.5 min relaunch wedged,
#     10 min pause ran first try).
set -u
PID=$(pgrep -f "runs/elect5_ddd.py" | head -1)
if [ -z "$PID" ]; then echo "no campaign process"; exit 1; fi
echo "SIGINT -> $PID at $(date -u +%H:%M:%S)"
kill -INT "$PID"
for i in $(seq 1 180); do
    kill -0 "$PID" 2>/dev/null || break
    sleep 10
done
if kill -0 "$PID" 2>/dev/null; then
    echo "still alive after 30 min; NOT escalating (wedge risk) — investigate"
    exit 2
fi
echo "campaign exited at $(date -u +%H:%M:%S); endpoint tail:"
tail -3 /root/repo/runs/elect5ddd_r5b.out
tail -1 /root/repo/runs/elect5ddd.stats
echo "worker-claim release pause: wait 10 min before the next chip job"
