import sys; sys.path.insert(0, "/root/repo")
import jax, json, time
jax.config.update("jax_platforms", "cpu")
from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine
B = Bounds(n_servers=4, n_values=1, max_term=2, max_log=0, max_msgs=1)
caps = DDDCapacities(block=1 << 17, table=1 << 22, flush=1 << 20, levels=128)
out = {}
for view in (None, "deadvotes"):
    cfg = CheckConfig(bounds=B, spec="election",
                      invariants=("NoTwoLeaders",), chunk=1024, view=view)
    t = time.time()
    r = DDDEngine(cfg, caps).check()
    out[str(view)] = dict(n=r.n_states, d=r.diameter,
                          viol=bool(r.violation), wall=round(time.time()-t, 1))
    print(json.dumps(out), flush=True)
