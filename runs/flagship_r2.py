"""Round-2 flagship re-verification: the reference raft.cfg universe
(3s/2v, full `Next`, t2/l1/m2, SYMMETRY Server), exhaustive, single chip.

Round 1 completed this space in ~6.4 h (94,396,461 orbits, diameter 57,
4 invariants hold).  This rerun validates the round-2 performance work
end-to-end: same verdicts, same counts, measured wall clock.

Usage: python runs/flagship_r2.py [resume]
Stats appended to runs/flagship_r2.stats; checkpoint every 5 min.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.paged_engine import PagedCapacities, PagedEngine

RUNS = os.path.dirname(os.path.abspath(__file__))
CKPT = os.path.join(RUNS, "flagship_r2.ckpt")
STATS = os.path.join(RUNS, "flagship_r2.stats")

CFG = CheckConfig(
    bounds=Bounds(n_servers=3, n_values=2, max_term=2, max_log=1,
                  max_msgs=2, max_dup=1),
    spec="full",
    invariants=("NoTwoLeaders", "LogMatching", "CommittedWithinLog",
                "LeaderCompleteness"),
    symmetry=("Server",), chunk=2048)

CAPS = PagedCapacities(ring=1 << 23, table=1 << 28, levels=128)


def main():
    resume = CKPT if (len(sys.argv) > 1 and sys.argv[1] == "resume") \
        else None
    sf = open(STATS, "a", buffering=1)
    eng = PagedEngine(CFG, CAPS)
    r = eng.check(on_progress=lambda s: sf.write(json.dumps(s) + "\n"),
                  checkpoint=CKPT, checkpoint_every_s=300.0,
                  resume=resume)
    print(json.dumps({
        "n_states": r.n_states, "diameter": r.diameter,
        "n_transitions": r.n_transitions, "complete": r.complete,
        "violation": r.violation.invariant if r.violation else None,
        "wall_s": round(r.wall_s, 1),
    }))


if __name__ == "__main__":
    main()
