"""VERDICT r2 missing #4 closure: measure the DDD lossy-filter probe's
share of the device step on the real chip, then decide the SURVEY §2.8
Pallas dedup/probe kernel question with numbers (the EP write-up is the
model: build-or-retire follows the measurement, either way recorded).

Method: time, separately and at flagship shapes (3s/2v full Next,
SYMMETRY Server, chunk 4096 → N = chunk*A candidate lanes; filter table
2^26 slots), the two pieces of the per-chunk program:

- ``step``: unpack → expand → canonicalize → pack → orbit fingerprint →
  invariants → constraint (kernels.build_step) — the compute the filter
  protects;
- ``filter``: ddd_engine._filter_insert — two-sort first-occurrence +
  one-gather bucket probe + insert at [N] against the 2^26-slot table.

Each timed warm over many iterations with block_until_ready.  The
filter fraction bounds what a Pallas probe kernel could save: if the
gather is a few percent of the step, the kernel cannot pay (XLA already
fuses the mask/select chain); if >20%, build it (VERDICT threshold).

Writes one JSON line to stdout; run on the real chip (no --cpu).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.ddd_engine import _filter_insert
from raft_tla_tpu.device_engine import _EMPTY, BUCKET
from raft_tla_tpu.models import interp, spec as S
from raft_tla_tpu.ops import kernels

CFG = CheckConfig(
    bounds=Bounds(n_servers=3, n_values=2, max_term=2, max_log=1,
                  max_msgs=2, max_dup=1),
    spec="full",
    invariants=("NoTwoLeaders", "LogMatching", "CommittedWithinLog",
                "LeaderCompleteness"),
    symmetry=("Server",), chunk=4096)
TABLE = 1 << 26
REPS = 30


def frontier_rows(n_rows: int) -> np.ndarray:
    """A representative frontier: BFS a few levels, cycle the states
    (init-only rows would leave most action guards disabled)."""
    bounds = CFG.bounds
    init = interp.init_state(bounds)
    seen, frontier = {init}, [init]
    rows = [interp.to_vec(init, bounds)]
    while len(rows) < n_rows:
        nxt = []
        for s in frontier:
            if not interp.constraint_ok(s, CFG.bounds):
                continue
            for _i, t in interp.successors(s, bounds, spec=CFG.spec):
                if t not in seen:
                    seen.add(t)
                    nxt.append(t)
                    rows.append(interp.to_vec(t, bounds))
                    if len(rows) >= n_rows:
                        break
            if len(rows) >= n_rows:
                break
        frontier = nxt or frontier
    return np.asarray(rows[:n_rows], np.int32)


def timed(fn, *args, reps=REPS):
    out = fn(*args)
    jax.block_until_ready(out)        # compile + warm
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main() -> None:
    A = len(S.action_table(CFG.bounds, CFG.spec))
    B = CFG.chunk
    N = B * A
    step = jax.jit(kernels.build_step(CFG.bounds, CFG.spec,
                                      tuple(CFG.invariants),
                                      CFG.symmetry))
    vecs = jnp.asarray(frontier_rows(B))
    t_step = timed(step, vecs)
    out = step(vecs)

    TB = TABLE // BUCKET
    tbl_hi = jnp.full((TB, BUCKET), _EMPTY, jnp.uint32)
    tbl_lo = jnp.full((TB, BUCKET), _EMPTY, jnp.uint32)
    kh = out["fp_hi"].reshape(N)
    kl = out["fp_lo"].reshape(N)
    act = out["valid"].reshape(N)
    filt = jax.jit(_filter_insert, donate_argnums=(0, 1))

    # donation consumes the table; rebuild per rep OUTSIDE the timing by
    # timing a non-donating variant instead (the probe gather dominates
    # either way; insert scatter identical)
    filt_nd = jax.jit(_filter_insert)
    t_filter = timed(filt_nd, tbl_hi, tbl_lo, kh, kl, act)

    frac = t_filter / (t_step + t_filter)
    print(json.dumps({
        "chunk": B, "lanes": A, "candidates": N, "table_slots": TABLE,
        "t_step_ms": round(t_step * 1e3, 3),
        "t_filter_ms": round(t_filter * 1e3, 3),
        "filter_fraction": round(frac, 4),
        "verdict": ("build the Pallas probe kernel" if frac > 0.20
                    else "filter is not the bottleneck — do not build"),
        "backend": jax.devices()[0].platform,
    }))


if __name__ == "__main__":
    main()
