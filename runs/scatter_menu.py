"""Round-4 device-perf design menu: with the filter-insert scatter
identified as the dominant cost (runs/filter_anatomy.out — ~28 ms of
device time per chunk vs ~5 ms sort + ~4.5 ms probe; cost tracks the
344k scatter UPDATES, not the 3.7k real inserts), measure the redesign
candidates before committing to one:

  G  in-engine baseline: the real jitted ddd segment program, per-chunk
  A  the engine's six output-compaction scatters, standalone
  B  filter insert as ONE combined [slots, 2] row scatter (also fixes
     the hi/lo chimera hazard of two independent scatters)
  C  compacted insert: sort-compact the 3.7k streamed rows, scatter a
     static S-row prefix (traffic-sound: overflow inserts drop)
  D  sort-based output compaction: one argsort + gathers + one
     dynamic_update_slice (no scatter at all)

Timing protocol per runs/filter_anatomy.py: sync = diff consecutive
block_until_ready stamps (includes the ~112 ms tunnel dispatch floor,
reported separately), async = amortized dispatch pipeline.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.ddd_engine import (DDDCapacities, DDDEngine,
                                     _filter_insert)
from raft_tla_tpu.device_engine import _EMPTY, BUCKET
from raft_tla_tpu.models import spec as S
from raft_tla_tpu.ops import kernels

from filter_ablation import CFG, TABLE, frontier_rows
from filter_anatomy import timed_sync, timed_async

I32 = jnp.int32
U32 = jnp.uint32
S_INS = 1 << 15          # static compacted-insert budget (C)


def main() -> None:
    out = {}
    A = len(S.action_table(CFG.bounds, CFG.spec))
    B = CFG.chunk
    N = B * A
    step = jax.jit(kernels.build_step(CFG.bounds, CFG.spec,
                                      tuple(CFG.invariants),
                                      CFG.symmetry))
    n_chunks = 16
    rows = frontier_rows(B * n_chunks)
    vecs = jnp.asarray(rows[:B])
    so = jax.block_until_ready(step(vecs))
    kh = so["fp_hi"].reshape(N)
    kl = so["fp_lo"].reshape(N)
    act = so["valid"].reshape(N)

    TB = TABLE // BUCKET
    fresh = lambda: (jnp.full((TB, BUCKET), _EMPTY, U32),
                     jnp.full((TB, BUCKET), _EMPTY, U32))
    th, tl = fresh()
    th, tl, strm = jax.block_until_ready(
        jax.jit(_filter_insert)(th, tl, kh, kl, act))
    strm_np = np.asarray(strm)
    out["stream_count"] = int(strm_np.sum())

    # -- G: the real segment program, per chunk -------------------------
    eng = DDDEngine(CFG, DDDCapacities(block=B * n_chunks, table=TABLE,
                                       seg_rows=N * n_chunks))
    fbuf = jnp.asarray(eng.schema.pack(rows, np))
    fcon = jnp.ones((B * n_chunks,), bool)
    fc = eng._init_filter()
    bufs = eng._make_bufs()

    def seg_once(fc, bufs):
        return eng._segment(fc, bufs, fbuf, fcon, jnp.int32(n_chunks),
                            jnp.int32(0), jnp.int32(B * n_chunks))
    fc2, bufs2, stats = jax.block_until_ready(seg_once(fc, bufs))  # warm
    out["seg_warm_chunks"] = int(stats.steps)
    out["seg_warm_cursor"] = int(stats.cursor)
    ts = []
    for _ in range(5):
        fcx = eng._init_filter()
        bufx = eng._make_bufs()
        jax.block_until_ready((fcx, bufx))
        t0 = time.perf_counter()
        fcx, bufx, statsx = seg_once(fcx, bufx)
        jax.block_until_ready(statsx)
        ts.append(time.perf_counter() - t0)
    out["G_segment_sync_ms"] = round(float(np.median(ts)) * 1e3, 3)
    out["G_per_chunk_ms_minus_floor"] = round(
        (float(np.median(ts)) * 1e3 - 112.0) / n_chunks, 3)

    # -- A: the six output scatters, standalone -------------------------
    P = eng.schema.P
    OCAP = N
    svecs_words = jnp.asarray(
        np.random.default_rng(0).integers(0, 1 << 30, (N, P),
                                          dtype=np.int64).astype(np.int32))

    def out_scatters(okh, okl, orw, opa, ola, oco, stream, kh, kl):
        pos = jnp.cumsum(stream.astype(I32)) - 1
        sl = jnp.where(stream, pos, OCAP)
        okh = okh.at[sl].set(kh, mode="drop")
        okl = okl.at[sl].set(kl, mode="drop")
        orw = orw.at[sl].set(svecs_words, mode="drop")
        opa = opa.at[sl].set(jnp.arange(N, dtype=I32) // A, mode="drop")
        ola = ola.at[sl].set(jnp.arange(N, dtype=I32) % A, mode="drop")
        oco = oco.at[sl].set(stream, mode="drop")
        return okh, okl, orw, opa, ola, oco

    jout = jax.jit(out_scatters, donate_argnums=(0, 1, 2, 3, 4, 5))
    mk = lambda: (jnp.zeros((OCAP,), U32), jnp.zeros((OCAP,), U32),
                  jnp.zeros((OCAP, P), I32), jnp.zeros((OCAP,), I32),
                  jnp.zeros((OCAP,), I32), jnp.zeros((OCAP,), bool))
    bufs0 = mk()
    jax.block_until_ready(jout(*bufs0, strm, kh, kl))   # warm, consume
    ts = []
    for _ in range(8):
        bufs0 = mk()
        jax.block_until_ready(bufs0)
        t0 = time.perf_counter()
        r = jout(*bufs0, strm, kh, kl)
        jax.block_until_ready(r)
        ts.append(time.perf_counter() - t0)
    out["A_output_scatters_sync_ms"] = round(
        float(np.median(ts)) * 1e3, 3)

    # -- B: combined [slots, 2] row scatter, full N updates -------------
    def ins_combined(tbl, kh, kl, stream, wslot):
        bidx = (kl & jnp.uint32(TB - 1)).astype(I32)
        flat = bidx * BUCKET + wslot
        upd = jnp.stack([kh, kl], axis=1)
        tgt = jnp.where(stream, flat, TB * BUCKET)
        return tbl.at[tgt].set(upd, mode="drop")

    wslot = jnp.asarray(
        np.random.default_rng(1).integers(0, BUCKET, N, dtype=np.int64)
        .astype(np.int32))
    jins = jax.jit(ins_combined, donate_argnums=(0,))
    mkc = lambda: jnp.full((TB * BUCKET, 2), _EMPTY, U32)
    c = mkc()
    jax.block_until_ready(jins(c, kh, kl, strm, wslot))
    ts = []
    for _ in range(8):
        c = mkc()
        jax.block_until_ready(c)
        t0 = time.perf_counter()
        c = jins(c, kh, kl, strm, wslot)
        jax.block_until_ready(c)
        ts.append(time.perf_counter() - t0)
    out["B_combined_scatter_fullN_sync_ms"] = round(
        float(np.median(ts)) * 1e3, 3)

    # -- C: compact then scatter S_INS rows -----------------------------
    def ins_compact(tbl, kh, kl, stream, wslot):
        order = jnp.argsort(~stream)            # stream-first, stable
        sel = order[:S_INS]
        ok = stream[sel]
        bidx = (kl[sel] & jnp.uint32(TB - 1)).astype(I32)
        flat = jnp.where(ok, bidx * BUCKET + wslot[sel], TB * BUCKET)
        upd = jnp.stack([kh[sel], kl[sel]], axis=1)
        return tbl.at[flat].set(upd, mode="drop")

    jcomp = jax.jit(ins_compact, donate_argnums=(0,))
    c = mkc()
    jax.block_until_ready(jcomp(c, kh, kl, strm, wslot))
    ts = []
    for _ in range(8):
        c = mkc()
        jax.block_until_ready(c)
        t0 = time.perf_counter()
        c = jcomp(c, kh, kl, strm, wslot)
        jax.block_until_ready(c)
        ts.append(time.perf_counter() - t0)
    out["C_compact_scatter_sync_ms"] = round(
        float(np.median(ts)) * 1e3, 3)

    # -- D: sort-based output compaction (argsort + gathers + dus) ------
    def out_sorted(okh, okl, orw, opa, ola, oco, stream, kh, kl):
        order = jnp.argsort(~stream)
        iota = jnp.arange(N, dtype=I32)
        okh = jax.lax.dynamic_update_slice(okh, kh[order], (0,))
        okl = jax.lax.dynamic_update_slice(okl, kl[order], (0,))
        orw = jax.lax.dynamic_update_slice(orw, svecs_words[order],
                                           (0, 0))
        opa = jax.lax.dynamic_update_slice(opa, (iota // A)[order], (0,))
        ola = jax.lax.dynamic_update_slice(ola, (iota % A)[order], (0,))
        oco = jax.lax.dynamic_update_slice(oco, stream[order], (0,))
        return okh, okl, orw, opa, ola, oco

    jsorted = jax.jit(out_sorted, donate_argnums=(0, 1, 2, 3, 4, 5))
    bufs0 = mk()
    jax.block_until_ready(jsorted(*bufs0, strm, kh, kl))
    ts = []
    for _ in range(8):
        bufs0 = mk()
        jax.block_until_ready(bufs0)
        t0 = time.perf_counter()
        r = jsorted(*bufs0, strm, kh, kl)
        jax.block_until_ready(r)
        ts.append(time.perf_counter() - t0)
    out["D_output_sortcompact_sync_ms"] = round(
        float(np.median(ts)) * 1e3, 3)

    out["dispatch_floor_ms_ref"] = 112.0
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
