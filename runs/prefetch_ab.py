"""A/B of the double-buffered upload prefetch (utils/prefetch.py,
RAFT_TLA_PREFETCH) — decides the prefetch_enabled auto policy.
Protocol per the sig-prune/megakernel/hostdedup rounds: chip-state
fiducials via ``bench.py --fiducial`` bracketing the session (now
including the pinned ``store_read_mb_s`` host probe), interleaved reps,
medians, per-rep byte-parity asserts.  Two gates:

(a) **single-thread-measurable — the block-boundary spike.**  A
    host+device microbench of the upload chain itself: per block
    boundary, the sync arm pays read rows + read constraint column +
    pad + ``device_put`` + ready inline, while the prefetch arm pays
    only ``take()`` (the chain ran behind the previous block's device
    work, and the h2d dispatch was already issued).  The headline
    regime is **frontier/disk** (`FileStore` — the external-memory
    mode where the read is a real disk read); the RAM regime
    (`HostStore`) is recorded alongside.  Statistic: worst and median
    block-boundary wall per arm, median across reps; PASS = prefetch
    worst boundary <= 0.8x sync worst in the disk regime.  Every taken
    buffer is asserted byte-equal to the sync arm's read, every block,
    every rep.

(b) **overlap — in-engine throughput.**  The flagship-shape DDD probe
    (chunk 4096, deadline per arm) with RAFT_TLA_PREFETCH off vs on,
    in BOTH retention modes; segment-stream n_states parity asserted
    on the common prefix; warm states/s excludes the compile segment;
    the on arm also reports the schema-v6 ``prefetch_hits`` /
    ``upload_wait_ms`` observability fields.  PASS = >= 1.10x warm
    states/s with nproc >= 2.  On an nproc=1 host the prefetch thread
    and the harvest loop time-slice one core, so the thread-overlap
    half is expected to REFUTE here (the hostdedup round measured the
    same shape honestly) — recorded as such, with the on-chip re-A/B
    queued alongside ROADMAP item 2's jobs.

Usage: python runs/prefetch_ab.py [--cpu] [reps]
Artifact: runs/prefetch_ab.out (RESULTS.md "Upload prefetch A/B").
"""
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

if "--cpu" in sys.argv:
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.utils import native
from raft_tla_tpu.utils.prefetch import BlockPrefetcher

_ints = [int(a) for a in sys.argv[1:] if a.isdigit()]
REPS = _ints[0] if _ints else 3
DEADLINE_S = 60.0                  # per in-engine arm

# gate (a) shape: 32 block boundaries of 2^16 rows x 64 lanes (the
# flagship state width class) + a width-1 constraint column — big
# enough that the read+pad+h2d chain is milliseconds, small enough to
# cycle many boundaries per rep
BROWS, NBLOCKS, P = 1 << 16, 32, 64


def _fiducial():
    """bench.py --fiducial in a child (fresh jit caches, pinned gates)."""
    bench = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
    try:
        out = subprocess.run(
            [sys.executable, bench, "--fiducial"], capture_output=True,
            text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS":
                 jax.default_backend()}).stdout
        return json.loads(out.strip().splitlines()[-1])
    except Exception as e:                       # fiducial is evidence,
        return {"fiducial_error": repr(e)}       # not a gate — record


results = {"platform": jax.devices()[0].platform, "reps": REPS,
           "nproc": os.cpu_count() or 1,
           "spike": {"block_rows": BROWS, "n_blocks": NBLOCKS,
                     "width": P},
           "inengine": {}}
results["fiducial_start"] = _fiducial()
print("fiducial_start:", json.dumps(results["fiducial_start"]),
      flush=True)

# -- gate (a): block-boundary upload-wall spikes ---------------------------
# Per regime (disk = FileStore = frontier retention's store; ram =
# HostStore = full retention's), one fixed pseudorandom level per rep;
# both arms walk the same blocks with the same simulated device work
# between boundaries (a jitted matmul chain, ~the expand+fingerprint
# wall of a block), so the only difference is WHERE the upload chain
# runs.  Per-boundary walls; statistic worst/median per arm, median
# across reps.
_mm = jax.jit(lambda x: jnp.tanh(x @ x))
_mx = jnp.asarray(np.random.default_rng(0)
                  .standard_normal((768, 768), np.float32))
_mm(_mx).block_until_ready()                   # compile outside timing


def _device_work():
    y = _mx
    for _ in range(4):
        y = _mm(y)
    y.block_until_ready()


def _mk_stores(regime, tmp, rows, con):
    if regime == "disk":
        st = native.FileStore(os.path.join(tmp, "rows.bin"), width=P,
                              reset=True)
        cs = native.FileStore(os.path.join(tmp, "con.bin"), width=1,
                              reset=True)
    else:
        st, cs = native.HostStore(P), native.HostStore(1)
    st.append(rows)
    cs.append(con)
    if regime == "disk":
        st.sync()
        cs.sync()
    return st, cs


spike_stats = {"disk": {"sync": [], "prefetch": []},
               "ram": {"sync": [], "prefetch": []}}
for regime in ("disk", "ram"):
    for rep in range(REPS):
        rng = np.random.default_rng(100 + rep)
        rows = rng.integers(-1000, 1000, size=(BROWS * NBLOCKS, P),
                            dtype=np.int32)
        con = rng.integers(0, 2, size=(BROWS * NBLOCKS, 1),
                           dtype=np.int32)
        with tempfile.TemporaryDirectory() as tmp:
            st, cs = _mk_stores(regime, tmp, rows, con)
            # sync arm: the old upload chain at every boundary
            walls_sync, sync_reads = [], []
            for b in range(NBLOCKS):
                _device_work()
                t0 = time.monotonic()
                rb = st.read(b * BROWS, BROWS)
                cb = cs.read(b * BROWS, BROWS)[:, 0].astype(bool)
                fb, fc = jax.device_put(rb), jax.device_put(cb)
                jax.block_until_ready((fb, fc))
                walls_sync.append(time.monotonic() - t0)
                sync_reads.append((rb, cb))
            # prefetch arm: engine-shaped loop — take, then schedule
            # the next block behind this block's device work
            pf_rows = [np.zeros((BROWS, P), np.int32) for _ in range(2)]
            pf_con = [np.zeros((BROWS,), bool) for _ in range(2)]

            def pf_load(start, n, slot, _st=st, _cs=cs):
                rb, cb = pf_rows[slot], pf_con[slot]
                rb[:n] = _st.read(start, n)
                cb[:n] = _cs.read(start, n)[:, 0]
                return jax.block_until_ready(
                    (jax.device_put(rb), jax.device_put(cb)))

            pf = BlockPrefetcher(pf_load)
            walls_pf = []
            try:
                pf.schedule(0, BROWS)
                for b in range(NBLOCKS):
                    _device_work()
                    t0 = time.monotonic()
                    fb, fc = pf.take(b * BROWS, BROWS)
                    walls_pf.append(time.monotonic() - t0)
                    if b + 1 < NBLOCKS:
                        pf.schedule((b + 1) * BROWS, BROWS)
                    # per-boundary byte parity vs the sync arm's read
                    rb, cb = sync_reads[b]
                    assert np.array_equal(np.asarray(fb), rb), \
                        "prefetch row-buffer parity failed"
                    assert np.array_equal(np.asarray(fc), cb), \
                        "prefetch constraint-buffer parity failed"
                hits = pf.hits
            finally:
                pf.close()
            st.close()
            cs.close()
        for arm, walls in (("sync", walls_sync), ("prefetch", walls_pf)):
            w = sorted(walls)
            spike_stats[regime][arm].append((w[len(w) // 2], w[-1]))
        print(f"{regime:4} rep {rep}: sync med "
              f"{spike_stats[regime]['sync'][-1][0] * 1e3:7.2f} ms "
              f"worst {spike_stats[regime]['sync'][-1][1] * 1e3:8.2f} ms"
              f"   prefetch med "
              f"{spike_stats[regime]['prefetch'][-1][0] * 1e3:7.2f} ms "
              f"worst "
              f"{spike_stats[regime]['prefetch'][-1][1] * 1e3:8.2f} ms "
              f"(hits {hits}/{NBLOCKS})", flush=True)

for regime in ("disk", "ram"):
    for arm in ("sync", "prefetch"):
        meds = sorted(m for m, _w in spike_stats[regime][arm])
        worsts = sorted(w for _m, w in spike_stats[regime][arm])
        spike_stats[regime][arm] = {
            "median_boundary_ms": round(meds[len(meds) // 2] * 1e3, 2),
            "worst_boundary_ms": round(worsts[len(worsts) // 2] * 1e3, 2)}
    results["spike"][regime] = spike_stats[regime]
disk_ratio = (results["spike"]["disk"]["prefetch"]["worst_boundary_ms"]
              / max(results["spike"]["disk"]["sync"]["worst_boundary_ms"],
                    1e-9))
results["spike"]["disk_prefetch_vs_sync_worst"] = round(disk_ratio, 3)
results["spike"]["gate_a_pass"] = disk_ratio <= 0.8
print(f"gate (a): disk worst boundary prefetch/sync {disk_ratio:.3f}x "
      f"-> {'PASS' if results['spike']['gate_a_pass'] else 'FAIL'}",
      flush=True)

# -- gate (b): in-engine overlap (states/s off vs on, both retentions) -----
from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine

cfg = CheckConfig(bounds=Bounds(n_servers=3, n_values=2, max_term=2,
                                max_log=1, max_msgs=2, max_dup=1),
                  spec="full",
                  invariants=("NoTwoLeaders", "LogMatching",
                              "CommittedWithinLog", "LeaderCompleteness"),
                  symmetry=("Server",), chunk=4096)
for retention in ("full", "frontier"):
    caps = DDDCapacities(block=1 << 18, table=1 << 22, flush=1 << 22,
                         levels=128, retention=retention)
    streams = {}
    results["inengine"][retention] = {}
    for mode in ("off", "on"):
        os.environ["RAFT_TLA_PREFETCH"] = mode
        stats: list = []
        t0 = time.monotonic()
        try:
            r = DDDEngine(cfg, caps).check(deadline_s=DEADLINE_S,
                                           on_progress=stats.append)
        finally:
            os.environ.pop("RAFT_TLA_PREFETCH", None)
        wall = time.monotonic() - t0
        streams[mode] = [s["n_states"] for s in stats]
        if len(stats) >= 2:          # warm rate, compile segment excluded
            d_states = stats[-1]["n_states"] - stats[0]["n_states"]
            d_wall = stats[-1]["wall_s"] - stats[0]["wall_s"]
        else:
            d_states, d_wall = r.n_states, wall
        rec = {"wall_s": round(wall, 2), "states": r.n_states,
               "level": stats[-1]["level"] if stats else 0,
               "states_per_sec": round(d_states / max(d_wall, 1e-9), 1),
               "segments": len(stats)}
        if mode == "on" and stats:
            rec["prefetch_hits"] = stats[-1].get("prefetch_hits")
            rec["upload_wait_ms"] = stats[-1].get("upload_wait_ms")
        results["inengine"][retention][mode] = rec
        print(f"inengine {retention:8} {mode:3}  {wall:7.2f} s  "
              f"{r.n_states} states to level {rec['level']}  "
              f"warm {rec['states_per_sec']:.0f}/s"
              + (f"  hits {rec.get('prefetch_hits')}"
                 f" wait {rec.get('upload_wait_ms')} ms"
                 if mode == "on" else ""), flush=True)
    n_common = min(len(streams["off"]), len(streams["on"]))
    assert n_common > 0, "an arm produced no segments"
    assert streams["off"][:n_common] == streams["on"][:n_common], \
        f"segment n_states parity failed ({retention})"
    results["inengine"][retention]["parity_segments"] = n_common
    ratio = round(
        results["inengine"][retention]["on"]["states_per_sec"]
        / max(results["inengine"][retention]["off"]["states_per_sec"],
              1e-9), 3)
    results["inengine"][retention]["on_vs_off_warm_rate"] = ratio
multi = (os.cpu_count() or 1) >= 2
worst_ratio = min(results["inengine"][r]["on_vs_off_warm_rate"]
                  for r in ("full", "frontier"))
results["inengine"]["gate_b_applicable"] = multi
results["inengine"]["gate_b_pass"] = bool(multi and worst_ratio >= 1.10)
print(f"gate (b): on/off warm rate full "
      f"{results['inengine']['full']['on_vs_off_warm_rate']:.3f}x / "
      f"frontier "
      f"{results['inengine']['frontier']['on_vs_off_warm_rate']:.3f}x, "
      f"nproc {os.cpu_count() or 1} -> "
      + ("PASS" if results["inengine"]["gate_b_pass"] else
         ("FAIL" if multi else
          "REFUTED on this host (nproc=1 — the prefetch thread and the "
          "harvest loop time-slice one core; on-chip re-A/B queued)")),
      flush=True)

results["fiducial_end"] = _fiducial()
print("fiducial_end:", json.dumps(results["fiducial_end"]), flush=True)
print(json.dumps(results))
