"""Real-chip validation + benchmark of the Pallas orbit kernel.

1. Bit-identity: scan path vs Pallas kernel on random domain states at
   3s and 5s bounds (compiled, not interpret).
2. Throughput: the 5-server election step (the elect5/config-#4 shape)
   with and without RAFT_TLA_PALLAS_ORBIT, warm, chunk 4096.

Run ONLY while no campaign owns the chip (one engine per process).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.ops import fingerprint as fpr
from raft_tla_tpu.ops import pallas_orbit
from raft_tla_tpu.ops import state as st
from raft_tla_tpu.ops import symmetry as sym

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests"))
from test_pallas_orbit import pack_batch, random_struct  # noqa: E402


def check_bounds(bounds, N=4096):
    rng = np.random.default_rng(11)
    struct = random_struct(bounds, N, rng)
    lay = st.Layout.of(bounds)
    consts = jnp.asarray(fpr.lane_constants(lay.width))
    ref_fn = jax.jit(sym.build_orbit_fp(bounds, ("Server",), consts,
                                        False))
    pal_fn = pallas_orbit.build_orbit_fp(bounds, ("Server",), False,
                                         interpret=False)
    if pal_fn is None:
        print(f"{bounds.n_servers}s: pallas kernel declined "
              f"(P > {pallas_orbit._MAX_COMPILED_PERMS} unrolled perms "
              "overflows the scoped-vmem stack on real TPUs) — scan "
              "path serves this shape")
        return
    js = {k: jnp.asarray(v) for k, v in struct.items()}
    vecs = jnp.asarray(pack_batch(struct, lay))

    t0 = time.monotonic()
    rh, rl = jax.device_get(ref_fn(js))
    t_ref_cold = time.monotonic() - t0
    t0 = time.monotonic()
    ph, pl_ = jax.device_get(pal_fn(vecs))
    t_pal_cold = time.monotonic() - t0
    assert (rh == ph).all() and (rl == pl_).all(), "BIT MISMATCH"

    reps = 20
    t0 = time.monotonic()
    for _ in range(reps):
        out = ref_fn(js)
    jax.block_until_ready(out)
    t_ref = (time.monotonic() - t0) / reps
    t0 = time.monotonic()
    for _ in range(reps):
        out = pal_fn(vecs)
    jax.block_until_ready(out)
    t_pal = (time.monotonic() - t0) / reps
    print(f"{bounds.n_servers}s: bit-identical on {N} rows; warm "
          f"scan {t_ref*1e3:.1f} ms vs pallas {t_pal*1e3:.1f} ms "
          f"({t_ref/t_pal:.1f}x); cold {t_ref_cold:.1f}/"
          f"{t_pal_cold:.1f} s")


def main():
    print("devices:", jax.devices())
    check_bounds(Bounds(n_servers=3, n_values=2, max_term=2, max_log=1,
                        max_msgs=2, max_dup=1))
    check_bounds(Bounds(n_servers=5, n_values=2, max_term=2, max_log=0,
                        max_msgs=2, max_dup=1))


if __name__ == "__main__":
    main()
