"""Scale validation for the CSR fair-lasso machinery (no chip needed):
C++ Tarjan SCC + delta-frontier reachability on a synthetic 10M-node /
30M-edge digraph — the size class the 5-server liveness quotient
measures at (runs/liveness5_probe.out extrapolation)."""
import json, os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
from raft_tla_tpu.models.liveness import _csr_reach
from raft_tla_tpu.utils import native

N, M = 10_000_000, 30_000_000
rng = np.random.default_rng(0)
src = rng.integers(0, N, M)
dst = rng.integers(0, N, M).astype(np.int64)
order = np.argsort(src, kind="stable")
src, dst = src[order], dst[order]
indptr = np.zeros(N + 1, np.int64)
np.cumsum(np.bincount(src, minlength=N), out=indptr[1:])
del src, order

t0 = time.time()
comp, nc = native.scc_csr(indptr, dst)
t_scc = time.time() - t0
t0 = time.time()
reach = _csr_reach(indptr, dst, 0, N)
t_reach = time.time() - t0
print(json.dumps({
    "nodes": N, "edges": M, "n_sccs": int(nc),
    "scc_wall_s": round(t_scc, 1), "reach_wall_s": round(t_reach, 1),
    "reachable": int(reach.sum()), "native": native.HAS_NATIVE}))
