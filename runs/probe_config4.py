"""Sizing probe for BASELINE config #4 (north star): full ``Next`` incl.
Drop/Duplicate faults, 5 servers / 2 values, t2 l1 m2, SYMMETRY Server.

Runs a deadline-bounded streamed-engine segment on the real chip and
prints per-level growth + warm orbit rate — the measured inputs of the
quantitative sizing memo (runs/northstar_sizing.md).  Usage:

    python runs/probe_config4.py [deadline_seconds]
"""

import json
import sys
import time

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.streamed_engine import StreamedCapacities, StreamedEngine


def main(deadline: float) -> None:
    cfg = CheckConfig(
        bounds=Bounds(n_servers=5, n_values=2, max_term=2, max_log=1,
                      max_msgs=2, max_dup=1),
        spec="full",
        invariants=("NoTwoLeaders", "LogMatching", "CommittedWithinLog",
                    "LeaderCompleteness"),
        symmetry=("Server",), chunk=2048)
    eng = StreamedEngine(cfg, StreamedCapacities(
        block=1 << 20, ring=1 << 22, table=1 << 26, levels=128))
    stats: list = []

    def on_progress(d):
        stats.append(d)
        print(json.dumps(d), file=sys.stderr, flush=True)

    t0 = time.monotonic()
    r = eng.check(deadline_s=deadline, on_progress=on_progress)
    print(json.dumps({
        "config": "baseline#4 5s/2v full Next t2l1m2 SYMMETRY Server",
        "orbits": r.n_states,
        "levels": r.levels,
        "complete": r.complete,
        "violation": r.violation is not None,
        "wall_s": round(time.monotonic() - t0, 1),
        "warm_orbits_per_sec": round(
            (stats[-1]["n_states"] - stats[0]["n_states"])
            / max(stats[-1]["wall_s"] - stats[0]["wall_s"], 1e-9), 1)
        if len(stats) >= 2 else None,
    }))


if __name__ == "__main__":
    main(float(sys.argv[1]) if len(sys.argv) > 1 else 120.0)
