"""Obs off-path overhead A/B (ISSUE 5 acceptance gate).

Claim under test: with no listener attached (no ``--events``, no
``on_progress``, phase timers off), the RunTelemetry integration costs
nothing measurable — ``tel.active`` is False so the engines skip every
per-segment device fetch, and ``phases.phase()`` returns a shared no-op
handle.  The priced arms then show what turning the instruments ON
costs: the events log (async writer + per-segment fetch), v8 trace
spans (host-side span emission through the same log — NO device syncs,
the pipelining survives), the phase timers (a device sync per
phase — the documented pipelining trade), and the live OpenMetrics
endpoint (a MetricsServer tailing the log from the same process — a
pure log READER, so the claim is metrics_over_off ~ events_over_off).

Protocol (the chip-state-fiducial discipline of RESULTS.md "sig-prune
A/B"): arms interleave round-robin so machine drift hits all arms
equally, and every rep carries a fiducial — a synthetic jitted step +
64 MB device copy timed immediately before the engine run — so a drifted
rep is visible in the artifact instead of silently biasing a mean.

Space: 3-server/2-value election t2/m2 (2,053,427 states, diameter 33),
device engine, chunk 1024 — ~60 s/rep on the container CPU, large
enough that a per-segment cost would integrate into the wall.

Usage: python runs/obs_overhead_ab.py [reps]   (default 3)
Appends one JSON line per rep + a summary line to runs/bench_obs_ab.out.
"""

import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.device_engine import Capacities, DeviceEngine
from raft_tla_tpu.obs.phases import ENV_PHASE_TIMERS
from raft_tla_tpu.obs.trace import ENV_TRACE

RUNS = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(RUNS, "bench_obs_ab.out")

CFG = CheckConfig(
    bounds=Bounds(n_servers=3, n_values=2, max_term=2, max_log=0,
                  max_msgs=2),
    spec="election", invariants=("NoTwoLeaders",), chunk=1024)
CAPS = Capacities(n_states=1 << 21, levels=64)
N_EXPECT = 2_053_427


def fiducial() -> dict:
    """Synthetic step + copy, jitted and timed warm (chip/CPU weather)."""
    x = jnp.arange(1 << 24, dtype=jnp.uint32)          # 64 MB

    @jax.jit
    def step(v):
        return (v * jnp.uint32(2654435761) ^ (v >> 7)).sum()

    step(x).block_until_ready()                        # compile
    t0 = time.monotonic()
    step(x).block_until_ready()
    step_ms = (time.monotonic() - t0) * 1e3
    t0 = time.monotonic()
    jnp.array(x, copy=True).block_until_ready()
    copy_ms = (time.monotonic() - t0) * 1e3
    return {"synthetic_step_ms": round(step_ms, 2),
            "copy_64mb_ms": round(copy_ms, 2)}


def run_arm(arm: str, tmp: str) -> float:
    events = None
    server = None
    os.environ.pop(ENV_PHASE_TIMERS, None)
    os.environ.pop(ENV_TRACE, None)
    if arm == "events+metrics":
        # The live-endpoint arm: a MetricsServer mounted over a FRESH
        # per-rep directory (so tail state never accumulates across
        # reps) with the snapshot loop running at its cadence — the
        # realistic always-on cost.  The server only ever READS the
        # log; the engine is configured identically to the events arm.
        from raft_tla_tpu.obs.openmetrics import MetricsServer
        sub = os.path.join(tmp, f"metrics-{time.monotonic_ns()}")
        os.makedirs(sub)
        events = os.path.join(sub, "tenant.events")
        server = MetricsServer(
            sub, port=0,
            snapshot_path=os.path.join(sub, "metrics.events"),
            interval_s=5.0)
    elif arm != "off":
        events = os.path.join(tmp, f"{arm}-{time.monotonic_ns()}.events")
    if arm == "events+timers":
        os.environ[ENV_PHASE_TIMERS] = "1"
    if arm == "events+trace":
        os.environ[ENV_TRACE] = "1"
    t0 = time.monotonic()
    r = DeviceEngine(CFG, CAPS).check(events=events)
    wall = time.monotonic() - t0
    if server is not None:
        server.close()                   # final poll+snapshot off the clock
    os.environ.pop(ENV_PHASE_TIMERS, None)
    os.environ.pop(ENV_TRACE, None)
    assert r.n_states == N_EXPECT and r.complete, (arm, r.n_states)
    return wall


def main():
    reps = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    arms = ("off", "events", "events+trace", "events+timers",
            "events+metrics")
    walls: dict = {a: [] for a in arms}
    with tempfile.TemporaryDirectory() as tmp, open(OUT, "a") as out:
        for rep in range(reps):
            for arm in arms:                 # interleaved: drift is shared
                fid = fiducial()
                wall = run_arm(arm, tmp)
                line = {"rep": rep, "arm": arm, "wall_s": round(wall, 2),
                        "platform": jax.default_backend(), **fid}
                print(json.dumps(line))
                out.write(json.dumps(line) + "\n")
                out.flush()
                walls[arm].append(wall)
        med = {a: statistics.median(w) for a, w in walls.items()}
        summary = {
            "summary": "obs_overhead_ab",
            "n_states": N_EXPECT,
            "reps": reps,
            "median_wall_s": {a: round(m, 2) for a, m in med.items()},
            "events_over_off": round(med["events"] / med["off"], 4),
            "trace_over_off": round(med["events+trace"] / med["off"], 4),
            "timers_over_off": round(med["events+timers"] / med["off"], 4),
            "metrics_over_off": round(med["events+metrics"] / med["off"],
                                      4),
        }
        print(json.dumps(summary))
        out.write(json.dumps(summary) + "\n")


if __name__ == "__main__":
    main()
