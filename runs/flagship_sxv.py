"""VERDICT r2 next #10: the flagship universe under SYMMETRY Server
Value — both axes, |G| = 3! * 2! = 12.

The Server-only flagship is 94,396,461 orbits (~566M raw states,
diameter 57, re-verified bit-identically round 2 in 42.4 min).  The
Server*Value quotient must be consistent: every SxV orbit count n_sxv
satisfies  raw_states = sum over sxv orbits of |orbit|, and since the
raw space is the same, n_sxv is bounded by [n_server/2, n_server]
(Value adds a factor <= 2! = 2).  Diameter must be <= 57 (quotient
paths only shorten).

Runs on the DDD engine with a wall deadline (the chip window is
shared with bench at round end); writes one JSON line per progress
flush to runs/flagship_sxv.stats and the final result to stdout.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine

RUNS = os.path.dirname(os.path.abspath(__file__))

CFG = CheckConfig(
    bounds=Bounds(n_servers=3, n_values=2, max_term=2, max_log=1,
                  max_msgs=2, max_dup=1),
    spec="full",
    invariants=("NoTwoLeaders", "LogMatching", "CommittedWithinLog",
                "LeaderCompleteness"),
    symmetry=("Server", "Value"), chunk=4096)


def main():
    deadline = float(sys.argv[1]) if len(sys.argv) > 1 else 3000.0
    sf = open(os.path.join(RUNS, "flagship_sxv.stats"), "a", buffering=1)
    # table 2^22: the round-4 filter measurement (runs/filter_inengine
    # .out) — larger tables only add per-chunk copy cost
    eng = DDDEngine(CFG, DDDCapacities(block=1 << 20, table=1 << 22,
                                       flush=1 << 22, levels=128))
    t0 = time.time()
    r = eng.check(deadline_s=deadline,
                  on_progress=lambda s: sf.write(json.dumps(s) + "\n"),
                  checkpoint=os.path.join(RUNS, "flagship_sxv.ckpt"),
                  checkpoint_every_s=600.0)
    print(json.dumps({
        "n_orbits": r.n_states, "diameter": r.diameter,
        "n_transitions": r.n_transitions, "complete": r.complete,
        "violation": r.violation.invariant if r.violation else None,
        "wall_s": round(time.time() - t0, 1),
        "levels": r.levels if r.complete else len(r.levels),
    }))


if __name__ == "__main__":
    main()
