"""BASELINE config #2 campaign, round-2 DDD attempt: 5-server election,
t2/m2, SYMMETRY Server — exhaustive, with no fingerprint-table ceiling.

The streamed-engine v3 run reached 131.3M orbits into level 26 before the
2^28 device-table ceiling (and a tunnel wedge) ended it; its checkpoint
did not survive the environment reset.  This restarts the space on the
DDD engine, whose exact dedup lives in host RAM (~15B-state capacity).

Usage: python runs/elect5_ddd.py [resume] [--seg-rows E] [--route K] [--cpu]
(--seg-rows E sets DDDCapacities.seg_rows = 2**E -- checkpoint-compatible.)
Checkpoints at runs/elect5ddd.ckpt every 15 min; stats stream appended to
runs/elect5ddd.stats (one JSON line per flush/level); run-event log
appended to runs/elect5ddd.events (tail it live with raft-tla-monitor).  ``--route K``
switches to the EP-routed step (DDDCapacities.route_rows=K) —
checkpoint-compatible either way (tests/test_ddd_engine.py::
test_routed_checkpoint_crosses_step_switch).
"""

import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine

RUNS = os.path.dirname(os.path.abspath(__file__))
CKPT = os.path.join(RUNS, "elect5ddd.ckpt")
STATS = os.path.join(RUNS, "elect5ddd.stats")
EVENTS = os.path.join(RUNS, "elect5ddd.events")

CFG = CheckConfig(
    bounds=Bounds(n_servers=5, n_values=2, max_term=2, max_log=0,
                  max_msgs=2, max_dup=1),
    spec="election",
    invariants=("NoTwoLeaders", "CommittedWithinLog"),
    symmetry=("Server",), chunk=4096)

# retention="frontier" (round 4): master keys in RAM (8 B/orbit), rows
# in disk-backed current+next level files, no trace links — the TLC
# campaign regime.  Lifts the ~1.5e9 RAM/disk ceilings the full-
# retention resume was dying under (73 GB RSS at 983M orbits) to ~7e9.
CAPS = DDDCapacities(block=1 << 20, table=1 << 22, seg_rows=1 << 19,
                     flush=1 << 23, levels=1 << 12, retention="frontier")


def main():
    args = sys.argv[1:]
    if "--cpu" in args:          # resume-path validation without a chip
        import argparse

        from raft_tla_tpu.check import _force_cpu
        _force_cpu(argparse.Namespace(cpu=True, devices=0))
        args.remove("--cpu")
    if "--seg-rows" in args:     # checkpoint-compatible dispatch sizing
        k = args.index("--seg-rows")
        if k + 1 >= len(args) or not args[k + 1].isdigit() \
                or not 15 <= int(args[k + 1]) <= 26:
            sys.exit("usage: elect5_ddd.py [resume] [--seg-rows E] "
                     "[--route K] [--cpu]  (E = log2 of the segment row "
                     "budget, 15-26; default 19)")
        global CAPS
        CAPS = dataclasses.replace(CAPS, seg_rows=1 << int(args[k + 1]))
        del args[k:k + 2]
    route = 0
    if "--route" in args:
        k = args.index("--route")
        if k + 1 >= len(args) or not args[k + 1].isdigit():
            sys.exit("usage: elect5_ddd.py [resume] [--route K] [--cpu]  "
                     "(K = routed candidate slots per chunk, integer)")
        route = int(args[k + 1])
        del args[k:k + 2]
    caps = dataclasses.replace(CAPS, route_rows=route) if route else CAPS
    resume = CKPT if args and args[0] == "resume" else None
    sf = open(STATS, "a", buffering=1)

    def on_progress(s):
        sf.write(json.dumps(s) + "\n")

    eng = DDDEngine(CFG, caps)
    r = eng.check(on_progress=on_progress, checkpoint=CKPT,
                  checkpoint_every_s=900.0, resume=resume,
                  events=EVENTS)
    print(json.dumps({
        "n_states": r.n_states, "diameter": r.diameter,
        "n_transitions": r.n_transitions, "complete": r.complete,
        "violation": r.violation.invariant if r.violation else None,
        "levels": r.levels, "wall_s": round(r.wall_s, 1),
    }))


if __name__ == "__main__":
    main()
