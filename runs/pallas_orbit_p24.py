"""ADVICE r2 #1 closure: measure the Pallas orbit kernel at P=24 (the
4-server Server-symmetry group) on the real chip — the
_MAX_COMPILED_PERMS=24 gate was extrapolated from P=6 success and a
P=120 VMEM failure, never measured at its own boundary.

Compares the Pallas kernel against the lax.scan orbit pass on identical
inputs (keys must be bit-identical) and times both warm.  Outcomes:
- compile + parity + timing  -> record, keep the gate at 24;
- Mosaic compile failure     -> lower the gate to the measured-good 6.

Writes one JSON line to stdout; run on the real chip (no --cpu).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.config import Bounds
from raft_tla_tpu.models import interp
from raft_tla_tpu.ops import fingerprint as fpr
from raft_tla_tpu.ops import state as st
from raft_tla_tpu.ops import symmetry as sym

BOUNDS = Bounds(n_servers=4, n_values=1, max_term=2, max_log=0,
                max_msgs=2)
N_ROWS = 4096
REPS = 20


def frontier_rows(n_rows: int) -> np.ndarray:
    init = interp.init_state(BOUNDS)
    seen, frontier = {init}, [init]
    rows = [interp.to_vec(init, BOUNDS)]
    while len(rows) < n_rows:
        nxt = []
        for s in frontier:
            if not interp.constraint_ok(s, BOUNDS):
                continue
            for _i, t in interp.successors(s, BOUNDS, spec="election"):
                if t not in seen:
                    seen.add(t)
                    nxt.append(t)
                    rows.append(interp.to_vec(t, BOUNDS))
                    if len(rows) >= n_rows:
                        break
            if len(rows) >= n_rows:
                break
        frontier = nxt or frontier
    return np.asarray(rows[:n_rows], np.int32)


def timed(fn, *args, reps=REPS):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main() -> None:
    lay = st.Layout.of(BOUNDS)
    consts = jnp.asarray(fpr.lane_constants(lay.width))
    rows = frontier_rows(N_ROWS)
    vecs = jnp.asarray(rows)

    scan_fp = sym.build_orbit_fp(BOUNDS, ("Server",), consts, False)

    @jax.jit
    def scan_path(v):
        structs = jax.vmap(lambda x: st.unpack(x, lay, jnp))(v)
        return scan_fp(structs)

    t_scan = timed(scan_path, vecs)
    sh, sl = (np.asarray(x) for x in scan_path(vecs))

    res = {"perms": 24, "rows": N_ROWS,
           "t_scan_ms": round(t_scan * 1e3, 3),
           "backend": jax.devices()[0].platform}
    try:
        from raft_tla_tpu.ops import pallas_orbit

        pal = pallas_orbit.build_orbit_fp(BOUNDS, ("Server",), False)
        if pal is None:
            res["pallas"] = "declined (gate)"
        else:
            pal_j = jax.jit(pal)
            t_pal = timed(pal_j, vecs)
            ph, pl = (np.asarray(x) for x in pal_j(vecs))
            res.update(
                t_pallas_ms=round(t_pal * 1e3, 3),
                keys_bit_identical=bool((ph == sh).all()
                                        and (pl == sl).all()),
                speedup_vs_scan=round(t_scan / t_pal, 3))
    except Exception as e:                      # Mosaic compile failure
        res["pallas_error"] = f"{type(e).__name__}: {e}"[:300]
    print(json.dumps(res))


if __name__ == "__main__":
    main()
