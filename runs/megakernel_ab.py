"""Sync-timed A/B of the Pallas whole-step megakernel
(ops/pallas_step.build_step_megakernel) — decides the
_megakernel_enabled auto policy.  The megakernel stages the EXACT
fused-step program (jax.make_jaxpr over ops/kernels.build_step at one
row-block shape) into a single pallas_call over 128-row VMEM-resident
blocks, so the candidate tensor round-trips to HBM once per step
instead of once per XLA fusion boundary.  Parity is by construction
(same jaxpr, re-evaluated per block) and asserted bit-for-bit anyway.

Three measurements, all under the r3/r4 protocol (block_until_ready
between reps, median of reps, chip-state fiducials via
``bench.py --fiducial`` bracketing the session so drift is visible in
the artifact instead of silently biasing a mean):

- step-level at the flagship shape (|G| = 6), ``mid`` and ``shallow``
  pools, under BOTH gate policies: ``pinned`` (prescan + sig-prune
  forced off — the bit-stable fiducial program) and ``auto`` (the
  program production actually builds on this backend);
- step-level at elect5 (|G| = 120) under ``auto`` only — the orbit
  scan dominates there and the staged program is what ships;
- in-engine: the bench.py northstar probe (DDD engine, flagship
  shape, chunk 4096) per arm with RAFT_TLA_MEGAKERNEL off vs on and
  RAFT_TLA_PHASE_TIMERS=1, comparing warm orbits/sec with per-phase
  attribution (upload/expand/export/dedup/snapshot) and asserting
  n_states prefix parity across every segment both arms completed.

``pct_vpu_peak`` headroom comes from the bracketing fiducials (the
measured elementwise ceiling, so the ratio cancels chip weather).

Usage: python runs/megakernel_ab.py [--cpu] [reps] [chunk]
Artifact: runs/megakernel_ab.out (RESULTS.md "Megakernel A/B").
"""
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

if "--cpu" in sys.argv:
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.models import interp
from raft_tla_tpu.ops import kernels

_ints = [int(a) for a in sys.argv[1:] if a.isdigit()]
REPS = _ints[0] if _ints else 5
B = _ints[1] if len(_ints) > 1 else 1024
DEADLINE_S = 150.0                 # per in-engine arm (northstar-style)

FLAGSHIP = (Bounds(n_servers=3, n_values=2, max_term=2, max_log=1,
                   max_msgs=2, max_dup=1),
            "full", ("NoTwoLeaders", "LogMatching",
                     "CommittedWithinLog", "LeaderCompleteness"))
ELECT5 = (Bounds(n_servers=5, n_values=2, max_term=2, max_log=0,
                 max_msgs=2, max_dup=1),
          "election", ("NoTwoLeaders", "CommittedWithinLog"))

_GATES = ("RAFT_TLA_PRESCAN", "RAFT_TLA_SIGPRUNE", "RAFT_TLA_MEGAKERNEL")


def _fiducial():
    """bench.py --fiducial in a child (fresh jit caches, pinned gates)."""
    bench = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
    try:
        out = subprocess.run(
            [sys.executable, bench, "--fiducial"], capture_output=True,
            text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS":
                 jax.default_backend()}).stdout
        return json.loads(out.strip().splitlines()[-1])
    except Exception as e:                       # fiducial is evidence,
        return {"fiducial_error": repr(e)}       # not a gate — record


def _pools(bounds, spec):
    """(mid, shallow) row pools, each exactly B rows (sigprune_ab)."""
    init = interp.init_state(bounds)
    frontier, seen, mid = [init], {init}, []
    shallow, depth = [init], 0
    while len(mid) < B:
        if not frontier:
            raise SystemExit(f"space exhausted below {B} distinct rows")
        nxt = []
        for s in frontier:
            if not interp.constraint_ok(s, bounds):
                continue
            for _i, t in interp.successors(s, bounds, spec=spec):
                if t not in seen:
                    seen.add(t)
                    nxt.append(t)
        frontier = nxt
        depth += 1
        if depth <= 2:
            shallow += [s for s in frontier
                        if interp.constraint_ok(s, bounds)]
        mid = [s for s in frontier if interp.constraint_ok(s, bounds)]
    mid_rows = np.stack([interp.to_vec(s, bounds) for s in mid[:B]])
    srows = np.stack([interp.to_vec(s, bounds) for s in shallow])
    shallow_rows = np.tile(srows, (-(-B // len(srows)), 1))[:B]
    return mid_rows, shallow_rows


def _set_policy(policy):
    for k in _GATES:
        os.environ.pop(k, None)
    if policy == "pinned":
        os.environ["RAFT_TLA_PRESCAN"] = "off"
        os.environ["RAFT_TLA_SIGPRUNE"] = "off"


def _time_step(bounds, spec, invs, vecs, policy):
    """(ms_xla, ms_mega), full-dict parity asserted bit-for-bit."""
    out, ref = {}, None
    for name, mega in (("xla", False), ("mega", True)):
        _set_policy(policy)          # gates are read at build time
        try:
            fn = jax.jit(kernels.build_step(bounds, spec, invs,
                                            ("Server",),
                                            megakernel=mega))
            r = fn(vecs)
            jax.block_until_ready(r)
        finally:
            for k in _GATES:
                os.environ.pop(k, None)
        got = {k: np.asarray(v) for k, v in r.items()}
        if ref is None:
            ref = got
        else:
            for k in ref:
                assert got[k].dtype == ref[k].dtype, k
                assert np.array_equal(got[k], ref[k]), k
        times = []
        for _ in range(REPS):
            t0 = time.monotonic()
            jax.block_until_ready(fn(vecs))
            times.append(time.monotonic() - t0)
        out[name] = sorted(times)[len(times) // 2]
    return out["xla"], out["mega"]


results = {"platform": jax.devices()[0].platform, "chunk": B,
           "reps": REPS, "step": {}, "inengine": {}}
results["fiducial_start"] = _fiducial()
print("fiducial_start:", json.dumps(results["fiducial_start"]),
      flush=True)

ARMS = [("flagship", FLAGSHIP, ("pinned", "auto")),
        ("elect5", ELECT5, ("auto",))]
for shape, (bounds, spec, invs), policies in ARMS:
    mid, shallow = _pools(bounds, spec)
    results["step"][shape] = {}
    for policy in policies:
        for pool, rows in (("mid", mid), ("shallow", shallow)):
            ms_x, ms_m = _time_step(bounds, spec, invs,
                                    jnp.asarray(rows), policy)
            results["step"][shape][f"{policy}/{pool}"] = {
                "ms_xla": round(ms_x * 1e3, 2),
                "ms_mega": round(ms_m * 1e3, 2),
                "mega_vs_xla": round(ms_x / ms_m, 3)}
            print(f"{shape:9} {policy:6} {pool:8} "
                  f"xla {ms_x * 1e3:8.2f} ms/chunk  "
                  f"mega {ms_m * 1e3:8.2f} ms/chunk  "
                  f"({ms_x / ms_m:5.2f}x)", flush=True)

# in-engine: the northstar probe per arm, fresh DDD engines (the gate
# is read at step-BUILD time), phase timers on for attribution — free
# on CPU (RESULTS.md "Obs off-path A/B": timers arm 0.999x), rerun
# timers-off before quoting chip numbers.  Parity: CPU chunk
# scheduling is deterministic, so the n_states stream must agree on
# every segment index both arms reached before their deadline.
from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine

cfg = CheckConfig(bounds=FLAGSHIP[0], spec="full",
                  invariants=FLAGSHIP[2], symmetry=("Server",),
                  chunk=4096)
caps = DDDCapacities(block=1 << 20, table=1 << 22, flush=1 << 22,
                     levels=128)
streams = {}
for mode in ("off", "on"):
    _set_policy("auto")
    os.environ["RAFT_TLA_MEGAKERNEL"] = mode
    os.environ["RAFT_TLA_PHASE_TIMERS"] = "1"
    stats: list = []
    t0 = time.monotonic()
    try:
        r = DDDEngine(cfg, caps).check(deadline_s=DEADLINE_S,
                                       on_progress=stats.append)
    finally:
        for k in _GATES + ("RAFT_TLA_PHASE_TIMERS",):
            os.environ.pop(k, None)
    wall = time.monotonic() - t0
    streams[mode] = [s["n_states"] for s in stats]
    phases: dict = {}
    for s in stats:
        for k, v in (s.get("phase_s") or {}).items():
            phases[k] = phases.get(k, 0.0) + v
    if len(stats) >= 2:              # warm rate, compile segment excluded
        d_orbits = stats[-1]["n_states"] - stats[0]["n_states"]
        d_wall = stats[-1]["wall_s"] - stats[0]["wall_s"]
    else:
        d_orbits, d_wall = r.n_states, wall
    results["inengine"][mode] = {
        "wall_s": round(wall, 2), "orbits": r.n_states,
        "level": stats[-1]["level"] if stats else 0,
        "orbits_per_sec": round(d_orbits / max(d_wall, 1e-9), 1),
        "segments": len(stats),
        "phase_s": {k: round(v, 2) for k, v in sorted(phases.items())}}
    print(f"inengine  {mode:3}  {wall:7.2f} s  {r.n_states} orbits "
          f"to level {results['inengine'][mode]['level']}  "
          f"warm {results['inengine'][mode]['orbits_per_sec']:.0f}/s  "
          f"phases {results['inengine'][mode]['phase_s']}", flush=True)
n_common = min(len(streams["off"]), len(streams["on"]))
assert n_common > 0, "an arm produced no segments"
assert streams["off"][:n_common] == streams["on"][:n_common], \
    "segment n_states parity failed"
results["inengine"]["parity_segments"] = n_common
results["inengine"]["mega_vs_xla_warm_rate"] = round(
    results["inengine"]["on"]["orbits_per_sec"]
    / max(results["inengine"]["off"]["orbits_per_sec"], 1e-9), 3)

results["fiducial_end"] = _fiducial()
print("fiducial_end:", json.dumps(results["fiducial_end"]), flush=True)
print(json.dumps(results))
