"""VERDICT r2 weak#5 'done' gate: an EventuallyLeader verdict on a
>=1M-state graph from the DDD-store export (no device-table ceiling).

The 3-server election t2/m2 universe: 2,053,427 states, 4,087,611
transitions (refbfs-pinned).  Writes one JSON line per verdict to
runs/liveness_2m.out.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import jax

jax.config.update("jax_platforms", "cpu")

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.ddd_engine import DDDCapacities
from raft_tla_tpu.models import liveness

cfg = CheckConfig(
    bounds=Bounds(n_servers=3, n_values=1, max_term=2, max_log=0,
                  max_msgs=2),
    spec="election", invariants=(), chunk=4096)
caps = DDDCapacities(block=1 << 17, table=1 << 22, flush=1 << 20,
                     levels=128)
t0 = time.time()
graph = liveness.ddd_graph(cfg, caps)
t_graph = time.time() - t0
print(json.dumps({"phase": "graph", "n_states": len(graph[0]),
                  "n_edges": sum(map(len, graph[1])),
                  "wall_s": round(t_graph, 1)}), flush=True)
for prop, wf in [("EventuallyLeader", ("Next",)),
                 ("EventuallyLeader", ()),
                 ("InfinitelyOftenLeader", ("Next",))]:
    t1 = time.time()
    r = liveness.check(cfg, prop, wf=wf, graph=graph)
    print(json.dumps({
        "prop": prop, "wf": list(wf), "holds": r.holds,
        "n_states": r.n_states, "n_edges": r.n_edges,
        "n_sccs_checked": r.n_sccs_checked,
        "cycle_len": len(r.violation.cycle) if r.violation else None,
        "wall_s": round(time.time() - t1, 1)}), flush=True)
