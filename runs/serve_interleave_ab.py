"""Serve dispatch-interleaving A/B (ISSUE 13 acceptance gate).

Claim under test: routing the serve/ executor's bin dispatches through
the async :class:`~raft_tla_tpu.serve.sched.DispatchScheduler` —
two-deep pipelined dispatch, speculative same-bin chunks, and bin
compiles moved to background threads — (a) leaves every lane's counts
and verdict byte-identical to a solo ``engine.Engine`` run of the same
cfg ON EVERY REP, and (b) delivers >= 1.15x the sequential baseline's
aggregate throughput on a multi-bin manifest.  The baseline arm is the
same executor at ``depth=1, compile_async=False`` — byte-for-byte the
PR 6 synchronous dispatch order — so the A/B isolates exactly the
pipelining + async-compile delta.

Protocol (RESULTS.md "sig-prune A/B" discipline): arms interleave
round-robin inside each rep so machine drift hits both equally, and
every arm measurement carries a fiducial (synthetic jitted step + 64 MB
device copy timed immediately before the arm) so a drifted rep is
visible in the artifact instead of silently biasing a mean.  Parity vs
the solo Engine references is asserted for BOTH arms on every rep, not
sampled.

Manifest: the PR 6 16-job/4-bin manifest (3,014-state toy x8, its
Server-symmetry quotient x4, a max_term=3 widening x2, a max_msgs=3
widening x2) — all-completing, so full byte-parity is well-defined.

Usage: python runs/serve_interleave_ab.py [reps]   (default 3)
Appends one JSON line per arm-rep + a summary to
runs/serve_interleave_ab.out.
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from raft_tla_tpu.config import Bounds, CheckConfig
from raft_tla_tpu.engine import Engine
from raft_tla_tpu.serve.batch import BatchExecutor, bin_key

RUNS = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(RUNS, "serve_interleave_ab.out")

CHUNK = 256                           # shared dispatch width, both arms


def _cfg(**kw):
    b = dict(n_servers=2, n_values=1, max_term=2, max_log=0, max_msgs=2)
    sym = kw.pop("symmetry", ())
    b.update(kw)
    return CheckConfig(bounds=Bounds(**b), spec="election",
                       invariants=("NoTwoLeaders",), symmetry=sym,
                       chunk=CHUNK)


TOY = _cfg()                          # 3,014 states, diameter 17
TOY_SYM = _cfg(symmetry=("Server",))  # its symmetry quotient
TOY_T3 = _cfg(max_term=3)             # term-widened universe
TOY_M3 = _cfg(max_msgs=3)             # channel-widened universe

JOBS = ([(f"toy-{i}", TOY) for i in range(8)]
        + [(f"sym-{i}", TOY_SYM) for i in range(4)]
        + [(f"t3-{i}", TOY_T3) for i in range(2)]
        + [(f"m3-{i}", TOY_M3) for i in range(2)])

ARMS = {
    # the PR 6 synchronous order: one dispatch in flight, lazy compiles
    "sequential": dict(depth=1, compile_async=False),
    # the tentpole: two-deep pipeline, AOT compiles on worker threads
    "interleaved": dict(depth=2, compile_async=True),
}


def fiducial() -> dict:
    """Synthetic step + copy, jitted and timed warm (chip/CPU weather)."""
    x = jnp.arange(1 << 24, dtype=jnp.uint32)          # 64 MB

    @jax.jit
    def step(v):
        return (v * jnp.uint32(2654435761) ^ (v >> 7)).sum()

    step(x).block_until_ready()                        # compile
    t0 = time.monotonic()
    step(x).block_until_ready()
    step_ms = (time.monotonic() - t0) * 1e3
    t0 = time.monotonic()
    jnp.array(x, copy=True).block_until_ready()
    copy_ms = (time.monotonic() - t0) * 1e3
    return {"synthetic_step_ms": round(step_ms, 2),
            "copy_64mb_ms": round(copy_ms, 2)}


def run_arm(arm: str) -> tuple:
    t0 = time.monotonic()
    ex = BatchExecutor(chunk=CHUNK, **ARMS[arm])
    out = ex.run(JOBS)
    wall = time.monotonic() - t0
    assert all(oc.status == "completed" for oc in out.values()), \
        {j: oc.status for j, oc in out.items()}
    return wall, {jid: oc.result for jid, oc in out.items()}, \
        ex.last_stats


def assert_parity(solo: dict, got: dict, arm: str) -> int:
    total = 0
    for jid, _cfg_ in JOBS:
        a, b = solo[jid], got[jid]
        for field in ("n_states", "diameter", "n_transitions"):
            assert getattr(a, field) == getattr(b, field), \
                (arm, jid, field, getattr(a, field), getattr(b, field))
        assert list(a.levels) == list(b.levels), (arm, jid)
        assert dict(a.coverage) == dict(b.coverage), (arm, jid)
        assert a.complete and b.complete and a.violation is None \
            and b.violation is None, (arm, jid)
        total += a.n_states
    return total


def main():
    reps = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    n_bins = len({bin_key(cfg) for _jid, cfg in JOBS})
    # solo Engine references once (deterministic): the parity target
    # both arms must hit on every rep
    solo = {jid: Engine(cfg).check() for jid, cfg in JOBS}
    walls: dict = {a: [] for a in ARMS}
    n_total = None
    with open(OUT, "a") as out:
        for rep in range(reps):
            for arm in ARMS:            # interleaved: drift is shared
                fid = fiducial()
                wall, results, stats = run_arm(arm)
                walls[arm].append(wall)
                n_total = assert_parity(solo, results, arm)
                line = {"rep": rep, "arm": arm, "wall_s": round(wall, 2),
                        "jobs": len(JOBS), "bins": n_bins,
                        "dispatches": stats["dispatches"],
                        "peak_inflight": stats["peak_inflight"],
                        "async_compiles": stats["async_compiles"],
                        "platform": jax.default_backend(), **fid}
                print(json.dumps(line))
                out.write(json.dumps(line) + "\n")
                out.flush()
        med = {a: statistics.median(w) for a, w in walls.items()}
        rate = {a: round(n_total / med[a], 1) for a in med}
        ratio = rate["interleaved"] / rate["sequential"]
        summary = {
            "summary": "serve_interleave_ab",
            "jobs": len(JOBS), "bins": n_bins, "chunk": CHUNK,
            "aggregate_states": n_total,
            "reps": reps,
            "parity": "byte-identical to solo on every rep, both arms",
            "median_wall_s": {a: round(m, 2) for a, m in med.items()},
            "aggregate_states_per_sec": rate,
            "interleaved_over_sequential": round(ratio, 4),
            "pass_ge_1.15": ratio >= 1.15,
        }
        print(json.dumps(summary))
        out.write(json.dumps(summary) + "\n")


if __name__ == "__main__":
    main()
