// Host-side native runtime for the TPU checker (SURVEY §2.8).
//
// Plays the role TLC's disk-backed `states/` directory plays for the
// reference workflow (reference .gitignore:2): an append-only store of every
// discovered state, addressed by discovery index, living in host RAM rather
// than HBM.  The device keeps only the active BFS levels (a ring) plus the
// fingerprint table; everything older pages out here through these calls.
// Parent/lane link arrays (TLC's predecessor links for counterexample
// traces) ride along, so trace reconstruction never touches the device.
//
// Also hosts the bit-identical FP64 fingerprint (two-lane multilinear +
// murmur3 fmix32, constants supplied by the Python side from
// ops/fingerprint.lane_constants): sharding routes states by fingerprint, so
// host and device hashes MUST agree bit-for-bit (ops/fingerprint.py
// docstring).  Exposed C ABI only; bound via ctypes (no pybind11 in the
// image).
//
// Memory layout: fixed-size blocks (BLOCK_ROWS rows each) held in a vector
// of unique_ptr — append never reallocates or copies existing rows, so read
// pointers stay valid across appends and capacity grows to host RAM.

#include <cstdint>
#include <cstring>
#include <memory>
#include <vector>

namespace {

constexpr int64_t BLOCK_ROWS = 1 << 16;

struct Store {
    int32_t width;                // int32 words per state row
    int64_t n_rows = 0;
    int64_t n_links = 0;
    std::vector<std::unique_ptr<int32_t[]>> blocks;        // state rows
    // Trace links, int64 parents: discovery indices passed 2^31 on the
    // round-3 flagship campaign (983.4M orbits with levels still
    // growing), so the 32-bit link was the binding state-count ceiling
    // of the whole DDD architecture (VERDICT r3 missing #2).
    std::vector<std::unique_ptr<int64_t[]>> parent_blocks;
    std::vector<std::unique_ptr<int32_t[]>> lane_blocks;

    explicit Store(int32_t w) : width(w) {}

    int32_t* row_ptr(int64_t r) {
        return blocks[r / BLOCK_ROWS].get() + (r % BLOCK_ROWS) * width;
    }
    int64_t* parent_ptr(int64_t r) {
        return parent_blocks[r / BLOCK_ROWS].get() + (r % BLOCK_ROWS);
    }
    int32_t* lane_ptr(int64_t r) {
        return lane_blocks[r / BLOCK_ROWS].get() + (r % BLOCK_ROWS);
    }
};

}  // namespace

extern "C" {

Store* store_create(int32_t width) { return new Store(width); }

void store_destroy(Store* s) { delete s; }

int64_t store_size(const Store* s) { return s->n_rows; }

// Append n rows of s->width int32s; returns the new row count.
int64_t store_append(Store* s, const int32_t* rows, int64_t n) {
    for (int64_t k = 0; k < n; ++k) {
        if (s->n_rows / BLOCK_ROWS >= (int64_t)s->blocks.size())
            s->blocks.emplace_back(new int32_t[BLOCK_ROWS * s->width]);
        std::memcpy(s->row_ptr(s->n_rows), rows + k * s->width,
                    sizeof(int32_t) * s->width);
        ++s->n_rows;
    }
    return s->n_rows;
}

void store_read(Store* s, int64_t start, int64_t n, int32_t* out) {
    for (int64_t k = 0; k < n; ++k)
        std::memcpy(out + k * s->width, s->row_ptr(start + k),
                    sizeof(int32_t) * s->width);
}

// Trace links: (int64 parent discovery index, int32 action lane).
int64_t store_append_links(Store* s, const int64_t* parent,
                           const int32_t* lane, int64_t n) {
    for (int64_t k = 0; k < n; ++k) {
        if (s->n_links / BLOCK_ROWS >= (int64_t)s->parent_blocks.size()) {
            s->parent_blocks.emplace_back(new int64_t[BLOCK_ROWS]);
            s->lane_blocks.emplace_back(new int32_t[BLOCK_ROWS]);
        }
        *s->parent_ptr(s->n_links) = parent[k];
        *s->lane_ptr(s->n_links) = lane[k];
        ++s->n_links;
    }
    return s->n_links;
}

void store_read_links(Store* s, int64_t start, int64_t n,
                      int64_t* parent_out, int32_t* lane_out) {
    for (int64_t k = 0; k < n; ++k) {
        parent_out[k] = *s->parent_ptr(start + k);
        lane_out[k] = *s->lane_ptr(start + k);
    }
}

// Walk a parent chain backwards from `from_row` to the root; returns chain
// length, writing discovery indices root-first into out (capacity out_cap).
int64_t store_trace_chain(Store* s, int64_t from_row, int64_t* out,
                          int64_t out_cap) {
    int64_t len = 0;
    for (int64_t cur = from_row; cur >= 0; ++len) {
        if (len >= out_cap) return -1;           // caller's buffer too small
        out[len] = cur;
        cur = *s->parent_ptr(cur);
    }
    // reverse to root-first order
    for (int64_t a = 0, b = len - 1; a < b; ++a, --b) {
        int64_t t = out[a];
        out[a] = out[b];
        out[b] = t;
    }
    return len;
}

// Bit-identical twin of ops/fingerprint.fingerprint (two-lane multilinear
// multiply-sum mod 2^32 + murmur3 fmix32).  c1/c2 are the lane_constants
// rows; seeds are _LANE_SEEDS.
static inline uint32_t fmix32(uint32_t h) {
    h ^= h >> 16;
    h *= 0x85EBCA6Bu;
    h ^= h >> 13;
    h *= 0xC2B2AE35u;
    h ^= h >> 16;
    return h;
}

void fingerprint_rows(const int32_t* rows, int64_t n, int32_t width,
                      const uint32_t* c1, const uint32_t* c2,
                      uint32_t seed1, uint32_t seed2,
                      uint32_t* hi_out, uint32_t* lo_out) {
    for (int64_t k = 0; k < n; ++k) {
        const int32_t* row = rows + k * width;
        uint32_t s1 = 0, s2 = 0;
        for (int32_t w = 0; w < width; ++w) {
            uint32_t v = (uint32_t)row[w];
            s1 += v * c1[w];
            s2 += v * c2[w];
        }
        hi_out[k] = fmix32(s1 + seed1);
        lo_out[k] = fmix32(s2 + seed2);
    }
}

// Iterative Tarjan SCC over a CSR graph (the liveness fair-lasso
// checker's scale path — Python per-node recursion tops out around a
// few 1e7 nodes; this runs the 1e8-node graphs the 5-server election
// quotient measures at).  comp_out[v] = component id; ids are assigned
// in Tarjan completion order (reverse topological), which the caller
// only uses for grouping.  Returns the number of components.
int64_t scc_tarjan(int64_t n, const int64_t* indptr, const int64_t* dst,
                   int64_t* comp_out) {
    std::vector<int64_t> num(n, -1), low(n), stk, frame_v, frame_e;
    std::vector<uint8_t> on_stk(n, 0);
    stk.reserve(1024);
    frame_v.reserve(1024);
    frame_e.reserve(1024);
    int64_t counter = 0, ncomp = 0;
    for (int64_t root = 0; root < n; ++root) {
        if (num[root] != -1) continue;
        frame_v.push_back(root);
        frame_e.push_back(indptr[root]);
        num[root] = low[root] = counter++;
        stk.push_back(root);
        on_stk[root] = 1;
        while (!frame_v.empty()) {
            int64_t u = frame_v.back();
            int64_t e = frame_e.back();
            if (e < indptr[u + 1]) {
                frame_e.back() = e + 1;
                int64_t v = dst[e];
                if (num[v] == -1) {
                    num[v] = low[v] = counter++;
                    stk.push_back(v);
                    on_stk[v] = 1;
                    frame_v.push_back(v);
                    frame_e.push_back(indptr[v]);
                } else if (on_stk[v] && num[v] < low[u]) {
                    low[u] = num[v];
                }
            } else {
                frame_v.pop_back();
                frame_e.pop_back();
                if (low[u] == num[u]) {
                    int64_t w;
                    do {
                        w = stk.back();
                        stk.pop_back();
                        on_stk[w] = 0;
                        comp_out[w] = ncomp;
                    } while (w != u);
                    ++ncomp;
                }
                if (!frame_v.empty()) {
                    int64_t p = frame_v.back();
                    if (low[u] < low[p]) low[p] = low[u];
                }
            }
        }
    }
    return ncomp;
}

}  // extern "C"
