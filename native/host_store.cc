// Host-side native runtime for the TPU checker (SURVEY §2.8).
//
// Plays the role TLC's disk-backed `states/` directory plays for the
// reference workflow (reference .gitignore:2): an append-only store of every
// discovered state, addressed by discovery index, living in host RAM rather
// than HBM.  The device keeps only the active BFS levels (a ring) plus the
// fingerprint table; everything older pages out here through these calls.
// Parent/lane link arrays (TLC's predecessor links for counterexample
// traces) ride along, so trace reconstruction never touches the device.
//
// Also hosts the bit-identical FP64 fingerprint (two-lane multilinear +
// murmur3 fmix32, constants supplied by the Python side from
// ops/fingerprint.lane_constants): sharding routes states by fingerprint, so
// host and device hashes MUST agree bit-for-bit (ops/fingerprint.py
// docstring).  Exposed C ABI only; bound via ctypes (no pybind11 in the
// image).
//
// Memory layout: fixed-size blocks (BLOCK_ROWS rows each) addressed
// through a two-level block directory of atomic pointers — append never
// reallocates or copies existing rows OR the directory itself, so read
// pointers stay valid across appends and capacity grows to host RAM
// (2^12 root entries x 2^12 blocks x 2^16 rows = 2^40 rows).
//
// Concurrency contract (the upload-prefetch disjointness precondition,
// utils/prefetch.py): ONE appender thread and any number of reader
// threads may run concurrently, provided every read targets rows below
// a size the reader observed via store_size() AFTER those rows were
// appended.  Appends publish block pointers and then the new n_rows
// with release stores; store_size() loads with acquire, so a reader
// that bounds-checks against an observed size sees fully-written rows.
// Concurrent reads of rows at or above the observed size (and
// multi-appender use) remain undefined.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <memory>
#include <vector>

namespace {

constexpr int64_t BLOCK_ROWS = 1 << 16;

// Two-level directory of heap blocks: a fixed root of atomic chunk
// pointers, each chunk a fixed array of atomic block pointers.  The
// single appender allocates chunks/blocks on demand and publishes the
// pointers with release stores; readers load with acquire.  Neither
// level ever moves, unlike a std::vector's backing array.
template <typename T>
struct BlockDir {
    static constexpr int64_t CHUNK = 1 << 12;  // blocks per chunk
    static constexpr int64_t ROOT = 1 << 12;   // chunks in the root

    std::atomic<std::atomic<T*>*> root[ROOT] = {};

    ~BlockDir() {
        for (int64_t c = 0; c < ROOT; ++c) {
            std::atomic<T*>* chunk =
                root[c].load(std::memory_order_relaxed);
            if (!chunk) break;
            for (int64_t b = 0; b < CHUNK; ++b)
                delete[] chunk[b].load(std::memory_order_relaxed);
            delete[] chunk;
        }
    }

    // Reader path: acquire loads pair with the appender's release
    // stores of the same pointers.
    T* block(int64_t b) const {
        std::atomic<T*>* chunk =
            root[b / CHUNK].load(std::memory_order_acquire);
        return chunk[b % CHUNK].load(std::memory_order_acquire);
    }

    // Appender path (single thread): allocate-and-publish on demand.
    T* ensure_block(int64_t b, int64_t elems) {
        std::atomic<T*>* chunk =
            root[b / CHUNK].load(std::memory_order_relaxed);
        if (!chunk) {
            chunk = new std::atomic<T*>[CHUNK]();
            root[b / CHUNK].store(chunk, std::memory_order_release);
        }
        T* blk = chunk[b % CHUNK].load(std::memory_order_relaxed);
        if (!blk) {
            blk = new T[elems];
            chunk[b % CHUNK].store(blk, std::memory_order_release);
        }
        return blk;
    }
};

struct Store {
    int32_t width;                // int32 words per state row
    std::atomic<int64_t> n_rows{0};
    std::atomic<int64_t> n_links{0};
    BlockDir<int32_t> blocks;     // state rows
    // Trace links, int64 parents: discovery indices passed 2^31 on the
    // round-3 flagship campaign (983.4M orbits with levels still
    // growing), so the 32-bit link was the binding state-count ceiling
    // of the whole DDD architecture (VERDICT r3 missing #2).
    BlockDir<int64_t> parent_blocks;
    BlockDir<int32_t> lane_blocks;

    explicit Store(int32_t w) : width(w) {}

    const int32_t* row_ptr(int64_t r) const {
        return blocks.block(r / BLOCK_ROWS) + (r % BLOCK_ROWS) * width;
    }
    const int64_t* parent_ptr(int64_t r) const {
        return parent_blocks.block(r / BLOCK_ROWS) + (r % BLOCK_ROWS);
    }
    const int32_t* lane_ptr(int64_t r) const {
        return lane_blocks.block(r / BLOCK_ROWS) + (r % BLOCK_ROWS);
    }
};

}  // namespace

extern "C" {

Store* store_create(int32_t width) { return new Store(width); }

void store_destroy(Store* s) { delete s; }

int64_t store_size(const Store* s) {
    return s->n_rows.load(std::memory_order_acquire);
}

// Append n rows of s->width int32s; returns the new row count.  The
// new size is release-published only after every row is fully written,
// so concurrent readers bounds-checking against store_size() never see
// a partially-copied row.
int64_t store_append(Store* s, const int32_t* rows, int64_t n) {
    int64_t r = s->n_rows.load(std::memory_order_relaxed);
    for (int64_t k = 0; k < n; ++k, ++r) {
        int32_t* blk = s->blocks.ensure_block(
            r / BLOCK_ROWS, BLOCK_ROWS * s->width);
        std::memcpy(blk + (r % BLOCK_ROWS) * s->width,
                    rows + k * s->width, sizeof(int32_t) * s->width);
    }
    s->n_rows.store(r, std::memory_order_release);
    return r;
}

void store_read(Store* s, int64_t start, int64_t n, int32_t* out) {
    for (int64_t k = 0; k < n; ++k)
        std::memcpy(out + k * s->width, s->row_ptr(start + k),
                    sizeof(int32_t) * s->width);
}

// Trace links: (int64 parent discovery index, int32 action lane).
// Same publish discipline as store_append.
int64_t store_append_links(Store* s, const int64_t* parent,
                           const int32_t* lane, int64_t n) {
    int64_t r = s->n_links.load(std::memory_order_relaxed);
    for (int64_t k = 0; k < n; ++k, ++r) {
        int64_t* pblk = s->parent_blocks.ensure_block(
            r / BLOCK_ROWS, BLOCK_ROWS);
        int32_t* lblk = s->lane_blocks.ensure_block(
            r / BLOCK_ROWS, BLOCK_ROWS);
        pblk[r % BLOCK_ROWS] = parent[k];
        lblk[r % BLOCK_ROWS] = lane[k];
    }
    s->n_links.store(r, std::memory_order_release);
    return r;
}

void store_read_links(Store* s, int64_t start, int64_t n,
                      int64_t* parent_out, int32_t* lane_out) {
    for (int64_t k = 0; k < n; ++k) {
        parent_out[k] = *s->parent_ptr(start + k);
        lane_out[k] = *s->lane_ptr(start + k);
    }
}

// Walk a parent chain backwards from `from_row` to the root; returns chain
// length, writing discovery indices root-first into out (capacity out_cap).
int64_t store_trace_chain(Store* s, int64_t from_row, int64_t* out,
                          int64_t out_cap) {
    int64_t len = 0;
    for (int64_t cur = from_row; cur >= 0; ++len) {
        if (len >= out_cap) return -1;           // caller's buffer too small
        out[len] = cur;
        cur = *s->parent_ptr(cur);
    }
    // reverse to root-first order
    for (int64_t a = 0, b = len - 1; a < b; ++a, --b) {
        int64_t t = out[a];
        out[a] = out[b];
        out[b] = t;
    }
    return len;
}

// Bit-identical twin of ops/fingerprint.fingerprint (two-lane multilinear
// multiply-sum mod 2^32 + murmur3 fmix32).  c1/c2 are the lane_constants
// rows; seeds are _LANE_SEEDS.
static inline uint32_t fmix32(uint32_t h) {
    h ^= h >> 16;
    h *= 0x85EBCA6Bu;
    h ^= h >> 13;
    h *= 0xC2B2AE35u;
    h ^= h >> 16;
    return h;
}

void fingerprint_rows(const int32_t* rows, int64_t n, int32_t width,
                      const uint32_t* c1, const uint32_t* c2,
                      uint32_t seed1, uint32_t seed2,
                      uint32_t* hi_out, uint32_t* lo_out) {
    for (int64_t k = 0; k < n; ++k) {
        const int32_t* row = rows + k * width;
        uint32_t s1 = 0, s2 = 0;
        for (int32_t w = 0; w < width; ++w) {
            uint32_t v = (uint32_t)row[w];
            s1 += v * c1[w];
            s2 += v * c2[w];
        }
        hi_out[k] = fmix32(s1 + seed1);
        lo_out[k] = fmix32(s2 + seed2);
    }
}

// Iterative Tarjan SCC over a CSR graph (the liveness fair-lasso
// checker's scale path — Python per-node recursion tops out around a
// few 1e7 nodes; this runs the 1e8-node graphs the 5-server election
// quotient measures at).  comp_out[v] = component id; ids are assigned
// in Tarjan completion order (reverse topological), which the caller
// only uses for grouping.  Returns the number of components.
int64_t scc_tarjan(int64_t n, const int64_t* indptr, const int64_t* dst,
                   int64_t* comp_out) {
    std::vector<int64_t> num(n, -1), low(n), stk, frame_v, frame_e;
    std::vector<uint8_t> on_stk(n, 0);
    stk.reserve(1024);
    frame_v.reserve(1024);
    frame_e.reserve(1024);
    int64_t counter = 0, ncomp = 0;
    for (int64_t root = 0; root < n; ++root) {
        if (num[root] != -1) continue;
        frame_v.push_back(root);
        frame_e.push_back(indptr[root]);
        num[root] = low[root] = counter++;
        stk.push_back(root);
        on_stk[root] = 1;
        while (!frame_v.empty()) {
            int64_t u = frame_v.back();
            int64_t e = frame_e.back();
            if (e < indptr[u + 1]) {
                frame_e.back() = e + 1;
                int64_t v = dst[e];
                if (num[v] == -1) {
                    num[v] = low[v] = counter++;
                    stk.push_back(v);
                    on_stk[v] = 1;
                    frame_v.push_back(v);
                    frame_e.push_back(indptr[v]);
                } else if (on_stk[v] && num[v] < low[u]) {
                    low[u] = num[v];
                }
            } else {
                frame_v.pop_back();
                frame_e.pop_back();
                if (low[u] == num[u]) {
                    int64_t w;
                    do {
                        w = stk.back();
                        stk.pop_back();
                        on_stk[w] = 0;
                        comp_out[w] = ncomp;
                    } while (w != u);
                    ++ncomp;
                }
                if (!frame_v.empty()) {
                    int64_t p = frame_v.back();
                    if (low[u] < low[p]) low[p] = low[u];
                }
            }
        }
    }
    return ncomp;
}

}  // extern "C"
