"""Headline benchmark: exhaustive model checking throughput on one chip.

Runs the device-resident checker (``raft_tla_tpu.device_engine``) over a
fixed suite of exhaustively-checkable Raft models (election sub-spec and the
full ``Next`` with crash/duplicate/drop faults — BASELINE.md configs #2/#4
scaled to single-chip HBM), invariants on, and reports warm throughput.
Each suite entry runs in its own subprocess: building several engines in one
process can wedge the TPU worker (see .claude/skills/verify/SKILL.md).

The reference publishes no performance numbers (BASELINE.md: ``"published":
{}``), so ``vs_baseline`` is measured against the driver's north-star budget:
the BASELINE.json target of an exhaustive, invariant-checked run in under
60 s.  ``vs_baseline = 60 / suite_wall_s`` — > 1 means the whole suite
finishes inside the north-star budget.

Prints exactly one JSON line on stdout; human detail goes to stderr.
"""

import json
import subprocess
import sys
import time

# Single source of truth for the suite; configs are built lazily in the
# child so the parent never imports jax.
SUITE_NAMES = ("election-3s", "full-2s-faults")
SUITE_SIZE = len(SUITE_NAMES)


def _suite():
    from raft_tla_tpu.config import Bounds, CheckConfig
    from raft_tla_tpu.device_engine import Capacities

    suite = (
        # (name, config, store capacity) — all verified to complete.
        ("election-3s",
         CheckConfig(bounds=Bounds(n_servers=3, n_values=1, max_term=2,
                                   max_log=0, max_msgs=1),
                     spec="election",
                     invariants=("NoTwoLeaders", "CommittedWithinLog"),
                     chunk=1024),
         Capacities(n_states=1 << 18, levels=64)),
        ("full-2s-faults",
         CheckConfig(bounds=Bounds(n_servers=2, n_values=2, max_term=2,
                                   max_log=1, max_msgs=2, max_dup=1),
                     spec="full",
                     invariants=("NoTwoLeaders", "LogMatching",
                                 "CommittedWithinLog"),
                     chunk=1024),
         Capacities(n_states=1 << 17, levels=64)),
    )
    assert tuple(e[0] for e in suite) == SUITE_NAMES
    return suite


def run_one(idx: int) -> None:
    """Child process: run suite entry ``idx``, print its JSON to stdout."""
    from raft_tla_tpu.device_engine import DeviceEngine

    name, cfg, caps = _suite()[idx]
    eng = DeviceEngine(cfg, caps)
    eng.check()                  # compile + cold run
    t0 = time.monotonic()
    r = eng.check()              # warm, timed
    wall = time.monotonic() - t0
    print(json.dumps({
        "name": name, "n_states": r.n_states, "diameter": r.diameter,
        "wall_s": wall, "violation": r.violation is not None,
    }))


def main() -> None:
    total_states = 0
    total_wall = 0.0
    for idx in range(SUITE_SIZE):
        proc = subprocess.run(
            [sys.executable, __file__, "--one", str(idx)],
            capture_output=True, text=True, timeout=900)
        if proc.returncode != 0:
            sys.stderr.write(proc.stderr)
            print(f"bench entry {idx} failed", file=sys.stderr)
            sys.exit(1)
        r = json.loads(proc.stdout.strip().splitlines()[-1])
        if r["violation"]:
            print(f"bench {r['name']}: unexpected invariant violation",
                  file=sys.stderr)
            sys.exit(1)
        total_states += r["n_states"]
        total_wall += r["wall_s"]
        print(f"{r['name']}: {r['n_states']} states, diameter "
              f"{r['diameter']}, {r['wall_s']:.2f}s warm "
              f"({r['n_states'] / r['wall_s']:,.0f} states/s)",
              file=sys.stderr)

    print(json.dumps({
        "metric": "exhaustive_check_states_per_sec_single_chip",
        "value": round(total_states / total_wall, 1),
        "unit": "states/s",
        "vs_baseline": round(60.0 / total_wall, 2),
    }))


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "--one":
        run_one(int(sys.argv[2]))
    else:
        main()
