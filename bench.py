"""Headline benchmark: north-star-shaped throughput on one chip.

Two parts, each in its own subprocess (building several engines in one
process can wedge the TPU worker — .claude/skills/verify/SKILL.md):

1. **North-star probe** (the headline): a time-boxed segment of the
   symmetric full-``Next`` reference universe (3s/2v, t2 l1 m2,
   SYMMETRY Server — the exact workload the flagship completed
   exhaustively at 94,396,461 orbits: 6.4 h round 1, 42.4 min measured
   round 2) on the DDD engine, warm orbits/s measured after the
   compile-carrying segment.  A probe still flatters the full run —
   rates decline as the host master-key set grows (this probe measured
   ~79k orbits/s where the complete rerun sustained ~37k end-to-end,
   a ~2x gap; the paged engine's gap was ~9x because its full-capacity
   device table also slows per-chunk dedup).  ``projected_flagship_
   wall_s`` is therefore a lower bound; the MEASURED wall is the
   42.4-min run recorded in RESULTS.md "Flagship re-verification".
2. **Toy suite** (secondary, kept for cross-round comparability):
   election-3s + full-2s on the HBM-resident engine, warm.

The reference publishes no performance numbers (BASELINE.md: ``"published":
{}``), so ``vs_baseline`` is measured against the driver's north-star
budget — exhaustive + invariant-checked in under 60 s.  Round 1 scored the
toy suite against that budget, which flattered (VERDICT r1 weak #6); the
headline is now **the projected wall for the known 94.4M-orbit flagship
space**: ``vs_baseline = 60 s / (94,396,461 / orbits_per_sec)``.  > 1
means the full reference universe, symmetric and fault-complete, would
finish inside the budget at the measured sustained rate.

Prints exactly one JSON line on stdout; human detail goes to stderr.
"""

import json
import os
import subprocess
import sys
import time

# The round-1 flagship exhaustive result (RESULTS.md): the reference
# raft.cfg universe under t2/l1/m2, SYMMETRY Server — the denominator for
# the projected-wall headline.
FLAGSHIP_ORBITS = 94_396_461
NORTHSTAR_DEADLINE_S = 120.0

SUITE_NAMES = ("election-3s", "full-2s-faults")
SUITE_SIZE = len(SUITE_NAMES)


def _suite():
    from raft_tla_tpu.config import Bounds, CheckConfig
    from raft_tla_tpu.device_engine import Capacities

    suite = (
        # (name, config, store capacity) — all verified to complete.
        ("election-3s",
         CheckConfig(bounds=Bounds(n_servers=3, n_values=1, max_term=2,
                                   max_log=0, max_msgs=1),
                     spec="election",
                     invariants=("NoTwoLeaders", "CommittedWithinLog"),
                     chunk=1024),
         Capacities(n_states=1 << 18, levels=64)),
        ("full-2s-faults",
         CheckConfig(bounds=Bounds(n_servers=2, n_values=2, max_term=2,
                                   max_log=1, max_msgs=2, max_dup=1),
                     spec="full",
                     invariants=("NoTwoLeaders", "LogMatching",
                                 "CommittedWithinLog"),
                     chunk=1024),
         Capacities(n_states=1 << 17, levels=64)),
    )
    assert tuple(e[0] for e in suite) == SUITE_NAMES
    return suite


def run_one(idx: int) -> None:
    """Child process: run toy-suite entry ``idx``, print its JSON."""
    from raft_tla_tpu.device_engine import DeviceEngine

    name, cfg, caps = _suite()[idx]
    eng = DeviceEngine(cfg, caps)
    eng.check()                  # compile + cold run
    t0 = time.monotonic()
    r = eng.check()              # warm, timed
    wall = time.monotonic() - t0
    print(json.dumps({
        "name": name, "n_states": r.n_states, "diameter": r.diameter,
        "wall_s": wall, "violation": r.violation is not None,
    }))


def run_fiducial() -> None:
    """Child process: the chip-state fiducial + utilization line.

    Three PINNED workloads whose times vary only with chip weather —
    never with bench-config or gate-policy drift — so any BENCH-round
    delta in the headline can be attributed to code vs chip:

    - ``copy_512mb_ms``: host->device transfer of a fixed 512 MB int32
      buffer (tunnel/DMA health);
    - ``synthetic_step_ms``: the fused step at the flagship shape
      (3s/2v t2 l1 m2, SYMMETRY Server, chunk 4096) on a fixed
      depth<=2 row pool, orbit-scan gates FORCED off so the program is
      bit-stable across rounds;
    - a saturating elementwise uint32 loop measuring the chip's
      achievable VPU word rate NOW — the denominator for
      ``pct_vpu_peak`` (a measured ceiling, not a datasheet constant,
      so the ratio cancels chip weather by construction);
    - ``flush_keys_per_sec``: host-only master-key dedup rate at a
      pinned flush shape (64 flushes of 2^16 pseudorandom keys, ~50%
      duplicates, through the flat single-thread MasterKeys — gate
      pinned off) so host-dedup deltas are code-attributable next to
      ``copy_512mb_ms``: if this fiducial moved, the host was the
      weather, not the keyset.
    - ``store_read_mb_s``: host-store block read bandwidth off a
      disk-backed FileStore (prefetch gate pinned off), so upload-
      prefetch deltas are code-attributable rather than page-cache
      weather.
    - ``d2h_export_rows_per_sec``: device->host harvest rate of an
      export-shaped segment payload at a pinned row count (device-dedup
      gate pinned off), so device-dedup A/B deltas — whose whole claim
      is "fewer rows cross this path" — are read against a measured
      per-row d2h cost rather than assumed PCIe datasheet numbers.

    ``words_per_sec`` is the orbit scan's analytic word traffic
    (chunk * actions * |G| * packed width) over the synthetic step
    time; ``pct_vpu_peak`` divides it by the measured elementwise
    ceiling.
    """
    import math

    # pin the step program: policy changes must not move the fiducial
    os.environ["RAFT_TLA_PRESCAN"] = "off"
    os.environ["RAFT_TLA_SIGPRUNE"] = "off"
    os.environ["RAFT_TLA_MEGAKERNEL"] = "off"
    os.environ["RAFT_TLA_HOSTDEDUP"] = "off"
    os.environ["RAFT_TLA_PREFETCH"] = "off"
    os.environ["RAFT_TLA_DEVDEDUP"] = "off"
    # trace_emit_overhead_us pins the DISABLED path (the default every
    # untraced run pays) — tracing must be off in this child.
    os.environ["RAFT_TLA_TRACE"] = "off"
    # the compile_wall_ms probe must measure a REAL XLA build: a warm
    # persistent compilation cache (serve/sched.enable_compile_cache,
    # RAFT_TLA_COMPILE_CACHE) would turn it into a disk-read fiducial.
    # Must be pinned before jax imports in this child.
    os.environ["JAX_ENABLE_COMPILATION_CACHE"] = "false"
    os.environ.pop("RAFT_TLA_COMPILE_CACHE", None)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_tla_tpu.config import Bounds
    from raft_tla_tpu.models import interp
    from raft_tla_tpu.models import spec as S
    from raft_tla_tpu.ops import kernels
    from raft_tla_tpu.ops import state as st

    def _median_ms(fn, reps=5):
        times = []
        for _ in range(reps):
            t0 = time.monotonic()
            jax.block_until_ready(fn())
            times.append(time.monotonic() - t0)
        return sorted(times)[len(times) // 2] * 1e3

    # -- fixed 512 MB host->device copy ------------------------------------
    host = np.zeros(512 * (1 << 20) // 4, dtype=np.int32)
    jax.block_until_ready(jax.device_put(host))          # warm the path
    copy_ms = _median_ms(lambda: jax.device_put(host), reps=3)

    # -- pinned-shape synthetic fused step ---------------------------------
    bounds = Bounds(n_servers=3, n_values=2, max_term=2, max_log=1,
                    max_msgs=2, max_dup=1)
    chunk, spec = 4096, "full"
    pool, frontier, seen = [], [interp.init_state(bounds)], set()
    for _ in range(2):                       # fixed depth-<=2 pool
        nxt = []
        for s in frontier:
            for _i, t in interp.successors(s, bounds, spec=spec):
                if t not in seen and interp.constraint_ok(t, bounds):
                    seen.add(t)
                    nxt.append(t)
        frontier = nxt
        pool += nxt
    rows = np.stack([interp.to_vec(s, bounds) for s in pool])
    vecs = jnp.asarray(np.tile(rows, (-(-chunk // len(rows)), 1))[:chunk])
    step = jax.jit(kernels.build_step(bounds, spec,
                                      ("NoTwoLeaders", "LogMatching"),
                                      ("Server",)))
    t_c = time.monotonic()
    jax.block_until_ready(step(vecs))                    # compile
    compile_ms = (time.monotonic() - t_c) * 1e3
    step_ms = _median_ms(lambda: step(vecs))

    # -- measured elementwise ceiling --------------------------------------
    x = jnp.arange(1 << 24, dtype=jnp.uint32)            # 64 MB resident
    iters = 64

    @jax.jit
    def vpu(v):
        return jax.lax.fori_loop(
            0, iters,
            lambda _i, a: (a ^ (a * jnp.uint32(0x9E3779B1)))
            + jnp.uint32(1), v)

    jax.block_until_ready(vpu(x))                        # compile
    vpu_ms = _median_ms(lambda: vpu(x))
    peak_words_per_sec = (1 << 24) * iters / (vpu_ms / 1e3)

    # orbit-scan analytic word traffic of the synthetic step
    A = len(S.action_table(bounds, spec))
    width = st.Layout.of(bounds).width
    G = math.factorial(bounds.n_servers)
    words_per_sec = chunk * A * G * width / (step_ms / 1e3)

    # -- pinned host master-key dedup rate ---------------------------------
    # Flat single-thread MasterKeys on a fixed pseudorandom stream (key
    # pool = 2x total keys => ~50% flush-over-flush duplicates, LSM
    # compactions included) — pure host CPU + memory bandwidth.
    from raft_tla_tpu.utils import keyset as _keyset
    _FLUSH, _NFLUSH = 1 << 16, 64
    rng = np.random.default_rng(0)
    flushes = [rng.integers(0, _FLUSH * _NFLUSH * 2, _FLUSH,
                            dtype=np.int64).astype(np.uint64)
               for _ in range(_NFLUSH)]
    _m = _keyset.MasterKeys()                            # warm once
    _m.dedup(flushes[0].copy())
    t_f = time.monotonic()
    m = _keyset.MasterKeys()
    for f in flushes:
        m.dedup(f)
    flush_keys_per_sec = _FLUSH * _NFLUSH / (time.monotonic() - t_f)

    # -- pinned host-store block read bandwidth ----------------------------
    # Disk-backed FileStore (the frontier-retention regime) read back in
    # 2^16-row blocks, prefetch gate pinned off above — pure host
    # filesystem/page-cache bandwidth, so prefetch A/B deltas are
    # code-attributable rather than page-cache weather.
    import tempfile
    from raft_tla_tpu.utils import native as _native
    _W, _BROWS, _NB = 32, 1 << 16, 16
    srng = np.random.default_rng(1)
    srows = srng.integers(0, 1 << 31, (_BROWS, _W), dtype=np.int64) \
        .astype(np.int32)
    with tempfile.TemporaryDirectory(prefix="bench_store_") as td:
        fs = _native.FileStore(os.path.join(td, "fid.rows"), _W,
                               reset=True)
        for _ in range(_NB):
            fs.append(srows)
        fs.sync()
        fs.read(0, _BROWS)                               # warm once
        t_r = time.monotonic()
        for b in range(_NB):
            fs.read(b * _BROWS, _BROWS)
        dt_r = time.monotonic() - t_r
        fs.close()
    store_read_mb_s = _NB * _BROWS * _W * 4 / (1 << 20) / dt_r

    # -- pinned d2h export-harvest rate ------------------------------------
    # The exact payload shape the ddd engines pull back per segment (two
    # uint32 key words + packed rows + parent/lane/constraint columns),
    # device_get at a pinned row count — the denominator the device-dedup
    # A/B (runs/devdedup_ab.py) reads its saved-rows claim against.
    _EROWS, _EREPS = 1 << 16, 8
    ebufs = (jnp.zeros((_EROWS,), jnp.uint32),
             jnp.zeros((_EROWS,), jnp.uint32),
             jnp.zeros((_EROWS, 32), jnp.int32),
             jnp.zeros((_EROWS,), jnp.int32),
             jnp.zeros((_EROWS,), jnp.int32),
             jnp.zeros((_EROWS,), jnp.int32))
    jax.block_until_ready(ebufs)
    jax.device_get(ebufs)                                # warm the path
    t_e = time.monotonic()
    for _ in range(_EREPS):
        jax.device_get(ebufs)
    d2h_rows_per_sec = _EROWS * _EREPS / (time.monotonic() - t_e)

    # -- pinned trace off-path cost ----------------------------------------
    # What every instrumentation site pays when tracing is OFF (the
    # default): a NULL_TRACER.span() context entry/exit — one shared
    # stateless handle, no allocation, no clock read.  Pinned so a
    # regression in the null path (the cost every untraced run pays at
    # every phase boundary) is code-attributable.  EXCLUDED from the
    # campaign drift ratio (supervisor._DRIFT_EXEMPT): sub-µs walls are
    # scheduler-hiccup noise at ratio scale.
    from raft_tla_tpu.obs.trace import NULL_TRACER
    _TRACE_ITERS = 200_000
    with NULL_TRACER.span("warm"):
        pass
    t_n = time.monotonic()
    for _ in range(_TRACE_ITERS):
        with NULL_TRACER.span("fiducial"):
            pass
    trace_emit_us = (time.monotonic() - t_n) * 1e6 / _TRACE_ITERS

    print(json.dumps({
        "copy_512mb_ms": round(copy_ms, 2),
        "compile_wall_ms": round(compile_ms, 1),
        "synthetic_step_ms": round(step_ms, 2),
        "words_per_sec": round(words_per_sec, 1),
        "pct_vpu_peak": round(100.0 * words_per_sec / peak_words_per_sec,
                              2),
        "flush_keys_per_sec": round(flush_keys_per_sec, 1),
        "store_read_mb_s": round(store_read_mb_s, 1),
        "d2h_export_rows_per_sec": round(d2h_rows_per_sec, 1),
        "trace_emit_overhead_us": round(trace_emit_us, 4),
    }))


def run_megakernel_probe() -> None:
    """Child process: both step builds at the fiducial shape.

    The pinned synthetic step (run_fiducial) measured twice — XLA build
    vs the Pallas megakernel build (ops/pallas_step.py), identical rows,
    orbit-scan gates forced off both times so the only difference is the
    dispatch path.  Emits ``megakernel_step_ms`` next to the XLA
    ``synthetic_step_ms`` twin so every fiducial-carrying bench round
    captures both paths (the megakernel A/B protocol, RESULTS.md
    "Megakernel A/B").  On CPU the megakernel runs under the Pallas
    interpreter — the honest number for the path a CPU run would take,
    not a TPU projection.  This pinned-gate ratio is a DRIFT TRACKER,
    not the policy decider: with gates pinned off the block-sliced
    program can show a win (1.13x on the container CPU) that the
    production auto-policy program inverts — the deciding comparison is
    runs/megakernel_ab.py's auto-policy arms + in-engine probe.
    """
    os.environ["RAFT_TLA_PRESCAN"] = "off"
    os.environ["RAFT_TLA_SIGPRUNE"] = "off"

    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_tla_tpu.config import Bounds
    from raft_tla_tpu.models import interp
    from raft_tla_tpu.ops import kernels

    def _median_ms(fn, reps=5):
        times = []
        for _ in range(reps):
            t0 = time.monotonic()
            jax.block_until_ready(fn())
            times.append(time.monotonic() - t0)
        return sorted(times)[len(times) // 2] * 1e3

    bounds = Bounds(n_servers=3, n_values=2, max_term=2, max_log=1,
                    max_msgs=2, max_dup=1)
    chunk, spec = 4096, "full"
    pool, frontier, seen = [], [interp.init_state(bounds)], set()
    for _ in range(2):
        nxt = []
        for s in frontier:
            for _i, t in interp.successors(s, bounds, spec=spec):
                if t not in seen and interp.constraint_ok(t, bounds):
                    seen.add(t)
                    nxt.append(t)
        frontier = nxt
        pool += nxt
    rows = np.stack([interp.to_vec(s, bounds) for s in pool])
    vecs = jnp.asarray(np.tile(rows, (-(-chunk // len(rows)), 1))[:chunk])
    args = (bounds, spec, ("NoTwoLeaders", "LogMatching"), ("Server",))
    out = {}
    for name, mega in (("xla_step_ms", False), ("megakernel_step_ms", True)):
        step = jax.jit(kernels.build_step(*args, megakernel=mega))
        jax.block_until_ready(step(vecs))                # compile
        out[name] = round(_median_ms(lambda: step(vecs)), 2)
    out["megakernel_vs_xla"] = round(out["xla_step_ms"] /
                                     max(out["megakernel_step_ms"], 1e-9), 3)
    print(json.dumps(out))


def run_walker_probe() -> None:
    """Child process: pinned walker-fleet throughput at the fiducial
    bounds.

    One solo ``Simulator`` (fused single-fetch path), compile carried by
    a warm-up run, then a measured run — ``walker_states_per_sec`` is
    the sustained sampled-state rate the simulation engines deliver on
    this chip today.  Same role as the megakernel column: a drift
    tracker next to the exhaustive fiducials, never the verdict (the
    deciding sharded-vs-solo comparison is runs/fleet_ab.py).
    """
    from raft_tla_tpu.config import Bounds, CheckConfig
    from raft_tla_tpu.simulate import Simulator

    cfg = CheckConfig(
        bounds=Bounds(n_servers=3, n_values=2, max_term=2, max_log=1,
                      max_msgs=2, max_dup=1),
        spec="full", invariants=("NoTwoLeaders", "LogMatching"))
    sim = Simulator(cfg, walkers=1024, depth=100, steps_per_dispatch=64,
                    seed=0)
    sim.run(1024)                                     # compile + warm
    r = sim.run(4096)
    print(json.dumps({
        "walker_states_per_sec": round(r.states_per_sec, 1),
        "walker_probe_states": r.n_states,
        "walker_probe_wall_s": round(r.wall_s, 3),
    }))


def run_northstar() -> None:
    """Child process: the time-boxed symmetric full-``Next`` 3s/2v probe.

    Runs on the DDD engine — no device dedup table, so the probe's gap
    to the full run is the host-merge growth alone (~2x at flagship
    scale) rather than the paged engine's ~9x full-capacity-table gap;
    see the module docstring and RESULTS.md "Flagship re-verification"
    for the measured 42.4-min complete-run ground truth.
    """
    from raft_tla_tpu.config import Bounds, CheckConfig
    from raft_tla_tpu.ddd_engine import DDDCapacities, DDDEngine

    cfg = CheckConfig(
        bounds=Bounds(n_servers=3, n_values=2, max_term=2, max_log=1,
                      max_msgs=2, max_dup=1),
        spec="full",
        invariants=("NoTwoLeaders", "LogMatching", "CommittedWithinLog",
                    "LeaderCompleteness"),
        symmetry=("Server",), chunk=4096)
    eng = DDDEngine(cfg, DDDCapacities(block=1 << 20, table=1 << 22,
                                       flush=1 << 22, levels=128))
    stats: list = []
    r = eng.check(deadline_s=NORTHSTAR_DEADLINE_S, on_progress=stats.append)
    # warm rate: orbits found after the first (compile-carrying) segment,
    # whenever the stats stream allows it — completed-in-box runs included
    if len(stats) >= 2:
        d_orbits = stats[-1]["n_states"] - stats[0]["n_states"]
        d_wall = stats[-1]["wall_s"] - stats[0]["wall_s"]
    else:                                   # single-segment run: no split
        d_orbits, d_wall = r.n_states, r.wall_s
    print(json.dumps({
        "orbits": r.n_states, "level": stats[-1]["level"] if stats else 0,
        "orbits_per_sec": d_orbits / max(d_wall, 1e-9),
        "violation": r.violation is not None,
        "complete": r.complete, "wall_s": r.wall_s,
    }))


# Set by main() once part 1 succeeds, so a later toy-suite failure still
# reports the measured headline instead of discarding it.
_partial: dict = {}


def _emit_error(reason: str) -> None:
    """The driver's scoreboard must be a parseable JSON line even when the
    chip is dead (VERDICT r4 weak #1: BENCH_r04.json was a traceback)."""
    print(json.dumps({
        "metric": "symmetric_fullnext_orbits_per_sec_single_chip",
        "value": _partial.get("value", 0.0), "unit": "orbits/s",
        "vs_baseline": _partial.get("vs_baseline", 0.0),
        "error": reason, **{k: v for k, v in _partial.items()
                            if k not in ("value", "vs_baseline")},
    }))
    sys.exit(0)


def _child(args: list, timeout: float, what: str) -> dict:
    """Run a bench child; on ANY failure emit the error JSON line and exit.

    A dead TPU tunnel makes the child's first dispatch hang forever — the
    in-engine deadline never fires because the deadline check itself sits
    behind a wedged ``block_until_ready`` — so the parent-side timeout is
    the only reliable box."""
    try:
        proc = subprocess.run([sys.executable, __file__, *args],
                              capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired as e:
        for stream in (e.stdout, e.stderr):   # partial output locates the wedge
            if stream:
                sys.stderr.write(stream if isinstance(stream, str)
                                 else stream.decode(errors="replace"))
        print(f"bench {what}: timed out after {timeout:.0f}s",
              file=sys.stderr)
        _emit_error(f"{what}_timeout")
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr)
        print(f"bench {what} failed (rc={proc.returncode})", file=sys.stderr)
        _emit_error(f"{what}_failed")
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        sys.stderr.write(proc.stdout)
        _emit_error(f"{what}_unparseable")


def main() -> None:
    # -- part 0: device preflight ------------------------------------------
    # ~60 s probe: a dead tunnel hangs jax device init forever; fail fast
    # with an explicit marker instead of letting the driver's timeout hit.
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); assert d; print(d[0].platform)"],
            capture_output=True, text=True, timeout=75)
        if proc.returncode != 0:
            sys.stderr.write(proc.stderr)
            print(f"bench preflight: device probe failed "
                  f"(rc={proc.returncode})", file=sys.stderr)
            _emit_error("device_probe_failed")
    except subprocess.TimeoutExpired:
        print("bench preflight: no usable device in 75s", file=sys.stderr)
        _emit_error("tpu_unavailable")
    print(f"bench preflight: device platform "
          f"{proc.stdout.strip()!r}", file=sys.stderr)

    # -- part 0.5: chip-state fiducial -------------------------------------
    # measured FIRST and merged into _partial immediately: a later wedge
    # still reports the chip-weather evidence the round needs
    fid = _child(["--fiducial"], timeout=300, what="fiducial")
    _partial.update(fid)
    print(f"fiducial: 512MB copy {fid['copy_512mb_ms']:.1f} ms, "
          f"step compile {fid.get('compile_wall_ms', 0.0):,.0f} ms, "
          f"synthetic step {fid['synthetic_step_ms']:.1f} ms, "
          f"{fid['words_per_sec']:,.0f} orbit-words/s "
          f"({fid['pct_vpu_peak']:.1f}% of measured VPU ceiling), "
          f"store read {fid.get('store_read_mb_s', 0.0):,.0f} MB/s",
          file=sys.stderr)
    # -- part 0.6: megakernel probe column ---------------------------------
    # both step builds at the fiducial shape (RESULTS.md "Megakernel
    # A/B").  Optional evidence: a probe failure — e.g. Mosaic refusing
    # the staged kernel on some future chip — becomes a recorded error
    # column, never the round's verdict.
    try:
        proc = subprocess.run([sys.executable, __file__, "--megakernel"],
                              capture_output=True, text=True, timeout=900)
        if proc.returncode == 0:
            mk = json.loads(proc.stdout.strip().splitlines()[-1])
            print(f"megakernel probe: xla {mk['xla_step_ms']:.1f} ms vs "
                  f"megakernel {mk['megakernel_step_ms']:.1f} ms "
                  f"({mk['megakernel_vs_xla']:.2f}x)", file=sys.stderr)
        else:
            sys.stderr.write(proc.stderr[-2000:])
            mk = {"megakernel_probe_error": f"rc={proc.returncode}"}
    except subprocess.TimeoutExpired:
        mk = {"megakernel_probe_error": "timeout"}
    except (ValueError, IndexError, KeyError):
        mk = {"megakernel_probe_error": "unparseable"}
    fid.update(mk)
    _partial.update(mk)
    # -- part 0.7: walker-throughput probe column ---------------------------
    # pinned simulation-mode rate (RESULTS.md "Fleet scaling A/B") — same
    # error-tolerant merge as the megakernel column: a probe failure is a
    # recorded column, never the round's verdict.
    try:
        proc = subprocess.run([sys.executable, __file__, "--walkers"],
                              capture_output=True, text=True, timeout=600)
        if proc.returncode == 0:
            wp = json.loads(proc.stdout.strip().splitlines()[-1])
            print(f"walker probe: {wp['walker_states_per_sec']:,.0f} "
                  "sampled states/s (1024 walkers, depth 100)",
                  file=sys.stderr)
        else:
            sys.stderr.write(proc.stderr[-2000:])
            wp = {"walker_probe_error": f"rc={proc.returncode}"}
    except subprocess.TimeoutExpired:
        wp = {"walker_probe_error": "timeout"}
    except (ValueError, IndexError, KeyError):
        wp = {"walker_probe_error": "unparseable"}
    fid.update(wp)
    _partial.update(wp)

    events_path = os.environ.get("RAFT_TLA_EVENTS")
    if events_path:
        # chip-weather evidence into the campaign's event log: the
        # monitor reads fiducials off run_start events to report drift;
        # the anchor/host pair (schema v8) additionally makes the bench
        # log clock-alignable in a raft-tla-trace collection, so chip
        # weather can be read against a traced run's timeline.
        try:
            from raft_tla_tpu.obs.events import append_event, git_sha
            from raft_tla_tpu.obs.trace import clock_anchor, host_context
            append_event(events_path, "run_start", engine="bench",
                         universe={}, spec="fiducial", invariants=[],
                         resumed=False, fiducials=fid,
                         anchor=clock_anchor(), host=host_context(),
                         **({"git_sha": git_sha()} if git_sha() else {}))
        except Exception as e:      # evidence channel, never the verdict
            print(f"bench: event append failed: {e!r}", file=sys.stderr)

    # -- part 1: the north-star probe --------------------------------------
    ns = _child(["--northstar"], timeout=480, what="northstar")
    if ns["violation"]:
        print("bench northstar: unexpected invariant violation",
              file=sys.stderr)
        _emit_error("northstar_violation")
    rate = ns["orbits_per_sec"]
    if ns["complete"]:
        # the probe ran the whole flagship space inside the box (a future-
        # fast regime, or a drifted probe config — either way the honest
        # number is the measured wall, not a projection)
        projected_flagship_wall = ns["wall_s"]
    else:
        projected_flagship_wall = FLAGSHIP_ORBITS / max(rate, 1e-9)
    print(f"northstar probe: {ns['orbits']:,} orbits to level "
          f"{ns['level']} in the {NORTHSTAR_DEADLINE_S:.0f}s box, warm "
          f"{rate:,.0f} orbits/s -> projected flagship "
          f"(94.4M-orbit) wall {projected_flagship_wall:,.0f}s",
          file=sys.stderr)
    # part 1 is the headline; keep it even if the toy suite fails below
    _partial.update({
        "value": round(rate, 1),
        "vs_baseline": round(60.0 / projected_flagship_wall, 4),
        "projected_flagship_wall_s": round(projected_flagship_wall, 1),
    })

    # -- part 2: the toy suite (secondary) ---------------------------------
    total_states = 0
    total_wall = 0.0
    for idx in range(SUITE_SIZE):
        r = _child(["--one", str(idx)], timeout=150, what=f"toy{idx}")
        if r["violation"]:
            print(f"bench {r['name']}: unexpected invariant violation",
                  file=sys.stderr)
            _emit_error(f"toy{idx}_violation")
        total_states += r["n_states"]
        total_wall += r["wall_s"]
        print(f"{r['name']}: {r['n_states']} states, diameter "
              f"{r['diameter']}, {r['wall_s']:.2f}s warm "
              f"({r['n_states'] / r['wall_s']:,.0f} states/s)",
              file=sys.stderr)

    payload = {
        "metric": "symmetric_fullnext_orbits_per_sec_single_chip",
        "value": round(rate, 1),
        "unit": "orbits/s",
        # 60 s north-star budget vs the projected wall for the KNOWN
        # 94.4M-orbit flagship space at the measured sustained rate
        "vs_baseline": round(60.0 / projected_flagship_wall, 4),
        "projected_flagship_wall_s": round(projected_flagship_wall, 1),
        "toy_suite_states_per_sec": round(total_states / total_wall, 1),
        "toy_suite_vs_60s_budget": round(60.0 / total_wall, 2),
        **fid,
    }
    print(json.dumps(payload))
    # The same payload the BENCH_r0*.json drivers record as "parsed",
    # written through the history store when RAFT_TLA_HISTORY is set —
    # so raft-tla-regress can verdict this round against the recorded
    # rounds (and the old BENCH files ingest as seed history).
    try:
        from raft_tla_tpu.obs.history import append_bench
        append_bench(payload, meta={"source": "bench.py"})
    except Exception as e:          # evidence channel, never the verdict
        print(f"bench: history append failed: {e!r}", file=sys.stderr)


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "--one":
        run_one(int(sys.argv[2]))
    elif len(sys.argv) == 2 and sys.argv[1] == "--northstar":
        run_northstar()
    elif len(sys.argv) == 2 and sys.argv[1] == "--fiducial":
        run_fiducial()
    elif len(sys.argv) == 2 and sys.argv[1] == "--megakernel":
        run_megakernel_probe()
    elif len(sys.argv) == 2 and sys.argv[1] == "--walkers":
        run_walker_probe()
    else:
        main()
